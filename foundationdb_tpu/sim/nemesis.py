"""Nemesis catalog: composable, seeded cross-subsystem fault actions.

Reference: the fault half of flow/sim2.actor.cpp plus the buggify'd
workload actors — but organised Jepsen-style as a *catalog of nemeses*:
each action is a small seeded actor that perturbs ONE subsystem (process
kills, storage reboots, pair/region partitions, clog storms, data-movement
kicks, DR failover, hot-range write storms, lane floods, tag-quota abuse,
cross-tenant probes, live consistency audits), and a campaign
(sim/campaigns.py) composes several of them against live workloads under
one TOML-declared, seed-replayable schedule.

Every random draw comes from the cluster loop's seeded RNG, so a failing
(spec, seed) pair replays bit-identically — the same guarantee the
FaultInjector gives, extended to cross-subsystem compositions.

Exactness contract: actions that *generate traffic* (WriteStorm,
TagQuotaAbuse, CrossTenantProbe, SystemProbe) keep exact accounting in the
shared ``NemesisContext`` and expose a ``verify(ctx, db)`` coroutine the
campaign runner calls after quiesce — conservation sums, admission bounds,
denial counts. Campaigns gate on these exact oracles (plus byte parity and
the workloads' own invariants), never on "it didn't crash".
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from foundationdb_tpu.core.errors import FdbError
from foundationdb_tpu.runtime.flow import all_of


class CampaignCheckFailed(FdbError):
    """An exact-oracle gate failed — the campaign found a bug."""

    code = 1501


@dataclass
class NemesisContext:
    """Shared state between a campaign's actions, workloads, and gates.

    The campaign runner attaches it to the cluster as
    ``cluster.nemesis_ctx`` so spec-driven workloads (e.g.
    FailoverZipfRepair) can coordinate with actions (e.g. DRSwitchover)
    without new plumbing through the workload interface."""

    cluster: object
    db: object
    extra: dict = field(default_factory=dict)  # dr agent, secondary db, ...
    counters: dict = field(default_factory=dict)  # exact accounting
    reports: list = field(default_factory=list)  # live consistency audits
    latencies: dict = field(default_factory=dict)  # lane -> [seconds]
    events: list = field(default_factory=list)  # (t, action, detail)
    defects: list = field(default_factory=list)  # live-observed violations
    flags: dict = field(default_factory=dict)  # e.g. {"failover": True}
    stopped: bool = False

    @property
    def loop(self):
        return self.cluster.loop

    def record(self, action: str, **detail) -> None:
        self.events.append((round(self.loop.now, 4), action, detail))

    def bump(self, key: str, n: int = 1) -> None:
        self.counters[key] = self.counters.get(key, 0) + n


class Nemesis:
    """One schedulable fault action.

    Schedule knobs shared by every action: ``at`` (virtual seconds before
    the first fire), ``every`` (mean inter-fire interval, jittered from
    the loop RNG), ``count`` (max fires; 0 = until the campaign stops the
    action). ``fire`` may return False to decline (precondition not met —
    does not consume the fire budget)."""

    name = "nemesis"

    def __init__(self, at: float = 0.0, every: float = 0.5, count: int = 1):
        self.at = at
        self.every = every
        self.count = count
        self.fired = 0

    async def run(self, ctx: NemesisContext) -> None:
        loop = ctx.loop
        if self.at:
            await loop.sleep(self.at)
        while not ctx.stopped and (self.count <= 0 or self.fired < self.count):
            ok = await self.fire(ctx)
            if ok is not False:
                self.fired += 1
            if self.count > 0 and self.fired >= self.count:
                return
            await loop.sleep(self.every * (0.5 + loop.rng.random()))

    async def fire(self, ctx: NemesisContext):  # pragma: no cover - interface
        raise NotImplementedError

    async def verify(self, ctx: NemesisContext, db) -> None:
        """Post-quiesce exact-oracle gate; default: nothing to check."""


# -- process faults -----------------------------------------------------------


class ProcessKiller(Nemesis):
    """Kill random generation processes (recovery must re-form the chain).
    Reuses the FaultInjector's safe-to-kill rule: never the last reachable
    tlog copy, never the last controller candidate."""

    name = "kill"

    def __init__(self, max_kills: int = 2, include_controller: bool = False,
                 **kw):
        super().__init__(count=max_kills, **kw)
        self.include_controller = include_controller
        self.kills: list[str] = []

    async def fire(self, ctx: NemesisContext):
        from foundationdb_tpu.sim.workloads import FaultInjector

        cluster = ctx.cluster
        rng = ctx.loop.rng
        gen = cluster.controller.generation
        victims = sorted(gen.heartbeat_eps)
        if self.include_controller and getattr(cluster, "cc_heartbeats", {}):
            victims.append(cluster.controller.identity)
        victim = victims[rng.randrange(len(victims))]
        helper = FaultInjector(cluster, max_kills=0)
        if not helper._safe_to_kill(gen, victim):
            return False
        self.kills.append(victim)
        ctx.bump("kills")
        ctx.record(self.name, victim=victim)
        cluster.net.kill(victim)


class ResolverKill(Nemesis):
    """Kill one RESOLVER of the current generation, anchored mid-traffic.

    The wave-commit composition this exists for (ISSUE 13): under the
    role-level global wave protocol a resolver dies BETWEEN edge
    exchanges — in-flight batches lose a shard mid-two-phase, the commit
    proxy's retries break, the batch fails into commit_unknown_result,
    and recovery re-forms the chain with fresh resolvers whose NEXT
    windows must again produce byte-identical global schedules (the
    campaign gates exact reordered/cycle counters accumulated AFTER the
    recovery). ``after_acked`` anchors the kill on the workloads' shared
    acked counter so it provably lands mid-stream."""

    name = "resolver_kill"

    def __init__(self, index: "int | None" = None, after_acked: int = 0,
                 **kw):
        kw.setdefault("count", 1)
        super().__init__(**kw)
        self.index = index
        self.after_acked = after_acked
        self.kills: list[str] = []

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        while ctx.counters.get("acked", 0) < self.after_acked:
            if ctx.stopped:
                return False
            await ctx.loop.sleep(0.02)
        gen = cluster.controller.generation
        victims = sorted(p for p in gen.heartbeat_eps if "resolver" in p)
        if not victims:
            return False
        idx = (self.index if self.index is not None
               else ctx.loop.rng.randrange(len(victims)))
        victim = victims[idx % len(victims)]
        self.kills.append(victim)
        ctx.bump("kills")
        ctx.bump("resolver_kills")
        ctx.record(self.name, victim=victim)
        cluster.net.kill(victim)


class StorageReboot(Nemesis):
    """Kill a random storage server's process, then revive it after
    ``down_s`` and restart its pull loop — the machine-reboot mode where
    the disk survives (cluster.heal_region's single-storage analogue)."""

    name = "storage_reboot"

    def __init__(self, down_s: float = 0.5, **kw):
        super().__init__(**kw)
        self.down_s = down_s

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        loop = ctx.loop
        procs = cluster.storage_procs()
        live = [
            (i, p) for i, p in enumerate(procs)
            if (cluster.process_prefix + p) not in loop.dead_processes
        ]
        if len(live) <= 1:
            return False  # keep at least one storage serving
        i, proc = live[loop.rng.randrange(len(live))]
        ctx.bump("storage_reboots")
        ctx.record(self.name, storage=proc)
        cluster.net.kill(proc)
        await loop.sleep(self.down_s)
        cluster.net.reboot(proc)
        loop.spawn(cluster.storages[i].run(),
                   process=cluster.process_prefix + proc,
                   name=f"storage{i}.run")


# -- network faults -----------------------------------------------------------


def _fault_procs(cluster) -> list[str]:
    gen = cluster.controller.generation
    return sorted(gen.heartbeat_eps) + cluster.storage_procs() + ["<main>"]


class PairPartition(Nemesis):
    """Transient partition between two random processes."""

    name = "pair_partition"

    def __init__(self, length: float = 0.6, **kw):
        super().__init__(**kw)
        self.length = length

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        rng = ctx.loop.rng
        procs = _fault_procs(cluster)
        a = procs[rng.randrange(len(procs))]
        b = procs[rng.randrange(len(procs))]
        if a == b:
            return False
        ctx.bump("partitions")
        ctx.record(self.name, a=a, b=b)
        cluster.net.partition(a, b)
        await ctx.loop.sleep(self.length)
        cluster.net.heal(a, b)


class RegionPartition(Nemesis):
    """Sever (or blackout) the active region for ``length`` virtual
    seconds; multi-region clusters must fail over and, on heal, catch the
    region back up. mode='partition' keeps the region alive-but-severed
    (the zombie-generation case); mode='fail' kills it outright."""

    name = "region_partition"

    def __init__(self, length: float = 3.0, mode: str = "partition", **kw):
        super().__init__(**kw)
        assert mode in ("partition", "fail"), mode
        self.length = length
        self.mode = mode

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        if not cluster.multi_region:
            return False
        region = cluster.active_region
        ctx.bump("region_faults")
        ctx.record(self.name, region=region, mode=self.mode)
        if self.mode == "partition":
            cluster.net.partition_region(region + "/")
            await ctx.loop.sleep(self.length)
            cluster.net.heal_region_partition(region + "/")
        else:
            cluster.net.fail_region(region + "/")
            await ctx.loop.sleep(self.length)
            cluster.heal_region(region)


class ClogStorm(Nemesis):
    """Clog several random links at once (slow-but-alive, no failure
    detector fires). ``targets``: optional list of [src_prefix, dst_prefix]
    pairs — every current process pair matching the prefixes is clogged,
    so campaigns can aim the storm at one subsystem boundary (e.g.
    proxy→resolver) across generations (role names carry .e{epoch})."""

    name = "clog_storm"

    def __init__(self, links: int = 3, factor: float = 80.0,
                 length: float = 0.4, targets: list | None = None, **kw):
        super().__init__(**kw)
        self.links = links
        self.factor = factor
        self.length = length
        self.targets = targets

    def _targeted_pairs(self, cluster) -> list[tuple[str, str]]:
        procs = _fault_procs(cluster)
        pairs = []
        for src_pfx, dst_pfx in self.targets:
            srcs = [p for p in procs if p.startswith(src_pfx)]
            dsts = [p for p in procs if p.startswith(dst_pfx)]
            pairs.extend((a, b) for a in srcs for b in dsts if a != b)
        return pairs

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        rng = ctx.loop.rng
        if self.targets:
            pairs = self._targeted_pairs(cluster)
        else:
            procs = _fault_procs(cluster)
            pairs = []
            for _ in range(self.links):
                a = procs[rng.randrange(len(procs))]
                b = procs[rng.randrange(len(procs))]
                if a != b:
                    pairs.append((a, b))
        if not pairs:
            return False
        for a, b in pairs:
            cluster.net.clog(a, b, factor=self.factor,
                             duration=self.length * (0.5 + rng.random()))
        ctx.bump("clogs", len(pairs))
        ctx.record(self.name, links=len(pairs))


# -- data-plane faults --------------------------------------------------------


class DataMovementKick(Nemesis):
    """Force shard moves of a key range between storage teams while
    traffic (and possibly an audit) runs — the DD dual-tag window under
    adversarial timing. Failed moves (partitioned member, mid-recovery)
    are recorded and tolerated: DD's own rollback is part of what the
    campaign exercises."""

    name = "data_movement"

    def __init__(self, begin: str = "", end: str = "\xff", **kw):
        super().__init__(**kw)
        self.begin = begin.encode() if isinstance(begin, str) else begin
        self.end = end.encode() if isinstance(end, str) else end

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        dd = getattr(cluster, "data_distributor", None)
        if dd is None:
            raise CampaignCheckFailed(
                "DataMovementKick needs dataDistribution = true")
        n = len(cluster.storage_eps)
        k = max(1, cluster.n_replicas)
        dst = tuple((self.fired + j) % n for j in range(k))
        try:
            await dd.move_shard(self.begin, self.end, dst)
            ctx.bump("moves_ok")
        except Exception as e:  # rollback path exercised; recorded
            ctx.bump("moves_failed")
            ctx.record(self.name + ".failed", error=type(e).__name__)
            return
        ctx.record(self.name, dst=list(dst))


class DeviceStall(Nemesis):
    """Transiently multiply every live resolver's modeled dispatch cost by
    ``factor`` for ``length`` virtual seconds — device-side interference
    (TPU preemption, a co-tenant's burst, an XLA recompile): dispatch
    capacity collapses while open-loop traffic keeps arriving, so the
    resolve queue must absorb the backlog, the ratekeeper's
    resolver_queue backpressure must engage, and the queue must fully
    drain once the device recovers. The composition that makes the
    sched × ratekeeper contract deterministically testable: without a
    stall, commit arrivals breathe in lockstep with dispatch completions
    (reads wait on storage catch-up, which waits on the commit pipeline)
    and depth self-limits right below the soft threshold."""

    name = "device_stall"

    def __init__(self, factor: float = 12.0, length: float = 1.5,
                 after_acked: int = 0, **kw):
        kw.setdefault("count", 1)
        super().__init__(**kw)
        self.factor = factor
        self.length = length
        # Wall-clock scheduling misses: cluster startup/recovery eats a
        # seed-dependent slice of the front of the run, so `at` can fire
        # a stall before the storm's arrival window even opens (campaign
        # smoke found depth peaking at 10-14 of 16). Anchoring on the
        # workloads' shared acked counter provably lands it mid-traffic.
        self.after_acked = after_acked

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        while ctx.counters.get("acked", 0) < self.after_acked:
            if ctx.stopped:
                return False
            await ctx.loop.sleep(0.02)
        saved = [(r, r.dispatch_cost_s) for r in cluster.resolvers]
        if not saved or not any(c for _r, c in saved):
            raise CampaignCheckFailed(
                "DeviceStall needs resolverDispatchCost > 0 (a stall on a "
                "zero-cost device model is a no-op)")
        for r, c in saved:
            r.dispatch_cost_s = c * self.factor
        ctx.bump("device_stalls")
        ctx.record(self.name, factor=self.factor, length=self.length)
        try:
            await ctx.loop.sleep(self.length)
        finally:
            for r, c in saved:
                r.dispatch_cost_s = c


class ConsistencyAudit(Nemesis):
    """Run the cluster-wide consistency checker LIVE, mid-storm — the
    composition the checker's moved_rescans / re-snapshot machinery exists
    for. ``kick_move`` additionally fires a shard move of the audited
    range while the scan is in flight, forcing the
    too_old → re-snapshot → wrong_shard_server → re-resolve path.

    Exact gate: any divergence is a defect (byte parity is unconditional
    — movement and clogs may slow the audit, never falsify it)."""

    name = "consistency_audit"

    def __init__(self, begin: str = "", end: str = "\xff",
                 kick_move: bool = False, chunk_bytes: int = 512,
                 bytes_per_s: float = 0.0, **kw):
        super().__init__(**kw)
        self.begin = begin.encode() if isinstance(begin, str) else begin
        self.end = end.encode() if isinstance(end, str) else end
        self.kick_move = kick_move
        self.chunk_bytes = chunk_bytes
        # Slow pacing (bytes/s) stretches the walk across virtual seconds
        # so scheduled faults reliably land MID-SCAN; 0 = default pacer.
        self.bytes_per_s = bytes_per_s

    async def fire(self, ctx: NemesisContext):
        from foundationdb_tpu.consistency.checker import ConsistencyChecker
        from foundationdb_tpu.consistency.scanner import RatekeeperPacer

        cluster = ctx.cluster
        pacer = None
        if self.bytes_per_s:
            pacer = RatekeeperPacer(ctx.loop,
                                    getattr(cluster, "ratekeeper_ep", None),
                                    bytes_per_s=self.bytes_per_s)
        checker = ConsistencyChecker(cluster, ctx.db, begin=self.begin,
                                     end=self.end,
                                     chunk_bytes=self.chunk_bytes,
                                     pacer=pacer)
        mover = None
        scanning = [True]
        if self.kick_move and getattr(cluster, "data_distributor", None):
            async def kick():
                # Keep flipping the audited range between teams for as
                # long as the scan runs: a single move can miss the scan
                # window (seed-dependent — campaign smoke found it), a
                # rotation cannot.
                rotation = 0
                while scanning[0]:
                    await ctx.loop.sleep(0.05 if rotation == 0 else 0.25)
                    n = len(cluster.storage_eps)
                    k = max(1, cluster.n_replicas)
                    dst = tuple((1 + rotation + j) % n for j in range(k))
                    rotation += 1
                    try:
                        await cluster.data_distributor.move_shard(
                            self.begin, self.end, dst)
                        ctx.bump("moves_ok")
                    except Exception:
                        ctx.bump("moves_failed")

            mover = ctx.loop.spawn(kick(), name="audit.kick_move")
        try:
            report = await checker.run()
        finally:
            scanning[0] = False
        if mover is not None:
            await mover
        ctx.reports.append(report)
        ctx.bump("audits")
        ctx.bump("moved_rescans", report["moved_rescans"])
        ctx.record(self.name, status=report["status"],
                   moved_rescans=report["moved_rescans"],
                   resnapshots=report["resnapshots"])
        if report["divergences"]:
            ctx.defects.append(
                f"live audit divergent: {report['divergences'][:2]!r}")

    async def verify(self, ctx: NemesisContext, db) -> None:
        bad = [r for r in ctx.reports if r["status"] == "divergent"]
        if bad:
            raise CampaignCheckFailed(
                f"{len(bad)} live audits reported divergence")


class DRSwitchover(Nemesis):
    """fdbdr switch mid-run: lock the primary, drain DR through every
    acked commit, byte-compare BOTH sides at the drain point (exact
    parity gate), then release clients to the secondary via
    ctx.flags['failover'].

    ``after_acked``: wait until the workloads' shared 'acked' counter
    reaches this many commits first, so the switchover provably lands
    mid-traffic (and, with FailoverZipfRepair, mid-repair)."""

    name = "dr_switchover"

    def __init__(self, after_acked: int = 0, **kw):
        kw.setdefault("count", 1)
        super().__init__(**kw)
        self.after_acked = after_acked
        self.parity: dict | None = None

    async def fire(self, ctx: NemesisContext):
        agent = ctx.extra.get("dr_agent")
        if agent is None:
            raise CampaignCheckFailed("DRSwitchover needs dr = true")
        while ctx.counters.get("acked", 0) < self.after_acked:
            if ctx.stopped:
                # Workloads finished below the anchor (spec mistuned):
                # decline instead of spinning past the end of the run —
                # verify() then fails crisply with "never fired".
                return False
            await ctx.loop.sleep(0.02)
        target = await agent.switchover()
        ctx.record(self.name, drained_through=target)
        # Parity snapshot at the drain point: primary is locked+quiesced,
        # the secondary static until the flag below releases the clients —
        # both sides are frozen, so a plain range compare is exact.
        src_rows = await self._dump(ctx.db)
        dst_rows = await self._dump(ctx.extra["dst_db"])
        self.parity = {
            "rows": len(src_rows),
            "equal": src_rows == dst_rows,
            "drained_through": target,
        }
        if src_rows != dst_rows:
            ctx.defects.append(
                f"DR parity broken at switchover: primary {len(src_rows)} "
                f"rows vs secondary {len(dst_rows)}")
        ctx.flags["failover"] = True

    @staticmethod
    async def _dump(db):
        async def body(tr):
            tr.set_option("lock_aware")
            return await tr.get_range(b"", b"\xff", limit=1_000_000)

        return await db.run(body)

    async def verify(self, ctx: NemesisContext, db) -> None:
        if self.parity is None:
            raise CampaignCheckFailed("DR switchover never fired")
        if not self.parity["equal"]:
            raise CampaignCheckFailed(
                f"byte parity failed at switchover: {self.parity}")


# -- adversarial traffic ------------------------------------------------------


class WriteStorm(Nemesis):
    """Hot-range write storm: ``clients`` concurrent streams of
    read-modify-write increments over ``keys`` keys under ``prefix`` at
    the given admission ``priority`` — the contention/lane-flood traffic
    shape. Exact accounting: idempotency markers make the conservation
    sum immune to commit_unknown_result retries, so verify() can require
    sum(keys) == acked increments EXACTLY even under kills.

    One fire runs the whole storm (count=1); schedule with ``at``."""

    name = "write_storm"

    def __init__(self, prefix: str = "storm/", keys: int = 2,
                 clients: int = 4, txns: int = 40,
                 priority: str = "default", open_loop: bool = False,
                 arrival_s: float = 0.003, blind: bool = False, **kw):
        kw.setdefault("count", 1)
        super().__init__(**kw)
        self.prefix = prefix.encode() if isinstance(prefix, str) else prefix
        self.keys = keys
        self.clients = clients
        self.txns = txns
        assert priority in ("system", "default", "batch"), priority
        self.priority = priority
        # Open-loop mode: transactions arrive on a seeded ~arrival_s
        # schedule as INDEPENDENT tasks (millions-of-clients shape) — the
        # arrival rate does not slow down when the cluster does, which is
        # what actually drives resolver-queue depth and the ratekeeper's
        # backpressure loop; closed-loop clients self-throttle and can't.
        self.open_loop = open_loop
        self.arrival_s = arrival_s
        # Blind mode — the true lane-flood shape: each txn is one
        # idempotent SET of its own unique key, NO reads. Read-bearing
        # txns convoy with the commit pipeline (reads wait on storage
        # catch-up, which trails resolution by a full dispatch — campaign
        # smoke measured the release waves), so only blind traffic keeps
        # arriving at client rate while the device stalls. Exactness is
        # preserved: unique keys make retries idempotent, so
        # count(keys) == acked is still an exact conservation gate.
        self.blind = blind

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    def _counter_key(self) -> str:
        return "storm_acked:" + self.prefix.decode()

    async def fire(self, ctx: NemesisContext):
        from foundationdb_tpu.core.types import strinc

        db = ctx.db
        loop = ctx.loop

        async def init(tr):
            self._set_priority(tr)
            tr.clear_range(self.prefix, strinc(self.prefix))
            for i in range(self.keys):
                tr.set(self._key(i), struct.pack("<q", 0))

        await db.run(init)

        async def one(cid: int, seq: int):
            if self.blind:
                unique = self.prefix + b"bl/%02d/%05d" % (cid, seq)

                async def body(tr, unique=unique):
                    self._set_priority(tr)
                    tr.set(unique, b"")
            else:
                k = self._key(loop.rng.randrange(self.keys))
                marker = (self.prefix + b"mk/%02d/%04d" % (cid, seq))

                async def body(tr, k=k, marker=marker):
                    self._set_priority(tr)
                    if await tr.get(marker) is not None:
                        return  # earlier attempt landed: exactly-once
                    tr.set(marker, b"")
                    (v,) = struct.unpack("<q", await tr.get(k))
                    tr.set(k, struct.pack("<q", v + 1))

            await db.run(body)
            ctx.bump(self._counter_key())
            ctx.bump("acked")

        if self.open_loop:
            tasks = []
            for seq in range(self.txns):
                tasks.append(loop.spawn(one(0, seq), name=f"storm.ol{seq}"))
                await loop.sleep(self.arrival_s * (0.5 + loop.rng.random()))
            await all_of(tasks)
        else:
            async def client(cid: int):
                for seq in range(self.txns // self.clients):
                    await one(cid, seq)

            await all_of([
                loop.spawn(client(i), name=f"storm.{self.priority}{i}")
                for i in range(self.clients)
            ])
        ctx.record(self.name, prefix=self.prefix.decode(),
                   acked=ctx.counters.get(self._counter_key(), 0))

    def _set_priority(self, tr) -> None:
        if self.priority == "batch":
            tr.set_option("priority_batch")
        elif self.priority == "system":
            tr.set_option("priority_system_immediate")

    async def verify(self, ctx: NemesisContext, db) -> None:
        acked = ctx.counters.get(self._counter_key(), 0)
        if self.blind:
            async def body(tr):
                rows = await tr.get_range(self.prefix + b"bl/",
                                          self.prefix + b"bl0",
                                          limit=1_000_000)
                return len(rows)

            landed = await db.run(body)
            if landed != acked:
                raise CampaignCheckFailed(
                    f"blind storm {self.prefix!r} not conserved: {landed} "
                    f"unique keys != {acked} acked txns (lost write)")
            return
        total = 0
        for i in range(self.keys):
            async def body(tr, i=i):
                return await tr.get(self._key(i))

            raw = await db.run(body)
            total += struct.unpack("<q", raw)[0] if raw else 0
        if total != acked:
            raise CampaignCheckFailed(
                f"write storm {self.prefix!r} not conserved: sum {total} != "
                f"{acked} acked increments (lost or double-applied update)")


class SystemProbe(Nemesis):
    """Latency probe stream on the system (or default) lane: one small
    txn per fire, commit latency recorded in ctx.latencies[lane]. The
    campaign gates the lane's p99 — bounded system-lane latency while a
    batch flood rages is the lanes subsystem's whole contract."""

    name = "system_probe"

    def __init__(self, lane: str = "system", **kw):
        kw.setdefault("every", 0.1)
        kw.setdefault("count", 0)
        super().__init__(**kw)
        assert lane in ("system", "default"), lane
        self.lane = lane

    async def fire(self, ctx: NemesisContext):
        db = ctx.db
        t0 = ctx.loop.now

        async def body(tr):
            if self.lane == "system":
                tr.set_option("priority_system_immediate")
            tr.set(b"probe/%s" % self.lane.encode(),
                   struct.pack("<q", self.fired))

        await db.run(body)
        ctx.latencies.setdefault(self.lane, []).append(ctx.loop.now - t0)
        ctx.bump("probes")


class BackpressureMonitor(Nemesis):
    """Samples the ratekeeper's resolver-queue signal every fire; verify()
    requires the backpressure loop ENGAGED (worst_resolver_queue reached
    ``engage_min``) and then DRAINED (final resolver queue empty) — the
    exact sched × network contract, not a liveness shrug."""

    name = "backpressure_monitor"

    def __init__(self, engage_min: int | None = None, **kw):
        kw.setdefault("every", 0.05)
        kw.setdefault("count", 0)
        super().__init__(**kw)
        self.engage_min = engage_min
        self.max_queue = 0
        self.engaged_reasons: set[str] = set()

    async def fire(self, ctx: NemesisContext):
        rk = getattr(ctx.cluster, "ratekeeper", None)
        if rk is None:
            return False
        self.max_queue = max(self.max_queue, rk.worst_resolver_queue)
        if rk.limiting_reason != "none":
            self.engaged_reasons.add(rk.limiting_reason)

    async def verify(self, ctx: NemesisContext, db) -> None:
        from foundationdb_tpu.runtime.ratekeeper import Ratekeeper

        engage_min = (Ratekeeper.RQ_SOFT if self.engage_min is None
                      else self.engage_min)
        if self.max_queue < engage_min:
            raise CampaignCheckFailed(
                f"resolver_queue backpressure never engaged: max depth "
                f"{self.max_queue} < {engage_min}")
        depths = [r.sched.queue_depth for r in ctx.cluster.resolvers]
        if any(depths):
            raise CampaignCheckFailed(
                f"resolver queues never drained: depths {depths}")
        ctx.record(self.name, max_queue=self.max_queue,
                   reasons=sorted(self.engaged_reasons))


class TagQuotaAbuse(Nemesis):
    """Quota abuse: set a tag tps quota, then flood GRV admission with
    that tag from ``clients`` greedy streams for one fire (count=1).
    verify(): admissions must stay under the token-bucket's EXACT upper
    bound quota·elapsed + burst — across recoveries (a kill must not
    reset the operator's quota; campaign-found defect class)."""

    name = "tag_quota_abuse"

    def __init__(self, tag: str = "abuser", quota: float = 12.0,
                 clients: int = 8, duration: float = 4.0, **kw):
        kw.setdefault("count", 1)
        super().__init__(**kw)
        self.tag = tag
        self.quota = quota
        self.clients = clients
        self.duration = duration
        self.admitted = 0
        self.elapsed = 0.0
        self.throttled_seen = 0  # high-water proxy tag_throttled sample

    async def fire(self, ctx: NemesisContext):
        cluster = ctx.cluster
        await cluster.ratekeeper_ep.set_tag_quota(self.tag, self.quota)
        # Let the proxies' rate poll pick the quota up before measuring:
        # the bucket exists only once get_rates() has been seen.
        await ctx.loop.sleep(0.25)
        # On an authz-armed cluster the abuser is a legitimate (tokened)
        # tenant of its own prefix — quota throttling and tenant
        # isolation are orthogonal, and an untokened abuser would be
        # denied at the read boundary before ever exercising the bucket.
        token = None
        priv = getattr(cluster, "authz_private_pem", None)
        if priv is not None:
            from foundationdb_tpu.runtime.authz import mint_token

            token = mint_token(priv, [b"quota/"], expires_at=1e12)
        loop = ctx.loop
        t0 = loop.now
        deadline = t0 + self.duration

        async def abuser(cid: int):
            while loop.now < deadline and not ctx.stopped:
                tr = ctx.db.transaction()
                tr.set_option("tag", self.tag)
                if token is not None:
                    tr.set_option("authorization_token", token)
                try:
                    await tr.get(b"quota/probe")
                except FdbError:
                    # Killed proxy / recovery: not an admission.
                    await loop.sleep(0.05)
                    continue
                self.admitted += 1
                ctx.bump("quota_admitted")

        async def sampler():
            # tag_throttled is per-proxy-generation (recoveries recruit
            # fresh proxies), so keep the max ever observed: any nonzero
            # sample proves the bucket actually pushed back.
            while loop.now < deadline and not ctx.stopped:
                self.throttled_seen = max(
                    self.throttled_seen,
                    max((p.tag_throttled for p in cluster.grv_proxies),
                        default=0))
                await loop.sleep(0.05)

        sampling = loop.spawn(sampler(), name="quota.sampler")
        await all_of([
            loop.spawn(abuser(i), name=f"quota.abuser{i}")
            for i in range(self.clients)
        ])
        await sampling
        self.elapsed = loop.now - t0
        ctx.record(self.name, admitted=self.admitted,
                   throttled_seen=self.throttled_seen,
                   elapsed=round(self.elapsed, 3))

    async def verify(self, ctx: NemesisContext, db) -> None:
        from foundationdb_tpu.runtime.grv_proxy import GrvProxy

        if self.elapsed <= 0:
            raise CampaignCheckFailed("quota abuse never ran")
        if self.admitted == 0:
            raise CampaignCheckFailed(
                "quota abuse admitted NOTHING — the gate is vacuous "
                "(abuser denied outright? cluster never served?)")
        if self.throttled_seen == 0:
            raise CampaignCheckFailed(
                "tag bucket never pushed back — the abuse load did not "
                "bind the quota, so enforcement was not exercised")
        # Token-bucket exact bound: rate·elapsed plus one full burst
        # allowance (bucket cap) and the per-client in-flight edge at the
        # deadline. Buckets start at ZERO on every proxy generation, and
        # tagged admission is deferred until a generation has seen rates
        # (the campaign-found fix in GrvProxy), so recoveries never add
        # burst — one cap covers the whole window.
        bound = (self.quota * self.elapsed + GrvProxy.MAX_TAG_TOKENS
                 + self.clients)
        if self.admitted > bound:
            raise CampaignCheckFailed(
                f"tag quota not enforced: {self.admitted} admissions > "
                f"bound {bound:.0f} (quota {self.quota}/s over "
                f"{self.elapsed:.2f}s) — quota lost (recovery?)")


class CrossTenantProbe(Nemesis):
    """Tenant-isolation probe under faults: carries a token scoped to its
    own prefix and, every fire, attempts an out-of-scope write that must
    end in a DEFINITIVE PermissionDenied whichever generation serves it.
    Any admission is cross-tenant leakage — an immediate defect."""

    name = "cross_tenant_probe"

    def __init__(self, prefix: str = "ctp/", **kw):
        kw.setdefault("every", 0.3)
        kw.setdefault("count", 0)
        super().__init__(**kw)
        self.prefix = prefix.encode() if isinstance(prefix, str) else prefix
        self._token = None
        self.denied = 0

    async def fire(self, ctx: NemesisContext):
        from foundationdb_tpu.core.errors import PermissionDenied
        from foundationdb_tpu.runtime.authz import mint_token

        priv = getattr(ctx.cluster, "authz_private_pem", None)
        if priv is None:
            raise CampaignCheckFailed(
                "CrossTenantProbe needs [campaign.cluster] authz = true")
        if self._token is None:
            self._token = mint_token(priv, [self.prefix], expires_at=1e12)

        async def in_scope(tr):
            tr.set_option("authorization_token", self._token)
            tr.set(self.prefix + b"n/%05d" % self.fired, b"v")

        await ctx.db.run(in_scope)  # the token itself works
        ctx.bump("acked")

        async def out_of_scope(tr):
            tr.set_option("authorization_token", self._token)
            tr.set(b"other-tenant/x", b"leak")

        try:
            await ctx.db.run(out_of_scope)
        except PermissionDenied:
            self.denied += 1
            return
        ctx.defects.append(
            f"cross-tenant write ADMITTED at t={ctx.loop.now:.2f}")

    async def verify(self, ctx: NemesisContext, db) -> None:
        if self.fired and self.denied != self.fired:
            raise CampaignCheckFailed(
                f"cross-tenant leakage: {self.fired - self.denied} of "
                f"{self.fired} out-of-scope writes admitted")


# -- registry (TOML name -> class + key mapping) ------------------------------

_COMMON = {"at": "at", "every": "every", "count": "count"}

NEMESIS_REGISTRY: dict[str, tuple[type, dict[str, str]]] = {
    "Kill": (ProcessKiller, {
        **_COMMON, "maxKills": "max_kills",
        "includeController": "include_controller",
    }),
    "ResolverKill": (ResolverKill, {
        **_COMMON, "index": "index", "afterAcked": "after_acked",
    }),
    "StorageReboot": (StorageReboot, {**_COMMON, "downSeconds": "down_s"}),
    "PairPartition": (PairPartition, {**_COMMON, "length": "length"}),
    "RegionPartition": (RegionPartition, {
        **_COMMON, "length": "length", "mode": "mode",
    }),
    "ClogStorm": (ClogStorm, {
        **_COMMON, "links": "links", "factor": "factor",
        "length": "length", "targets": "targets",
    }),
    "DeviceStall": (DeviceStall, {
        **_COMMON, "factor": "factor", "length": "length",
        "afterAcked": "after_acked",
    }),
    "DataMovementKick": (DataMovementKick, {
        **_COMMON, "begin": "begin", "end": "end",
    }),
    "ConsistencyAudit": (ConsistencyAudit, {
        **_COMMON, "begin": "begin", "end": "end",
        "kickMove": "kick_move", "chunkBytes": "chunk_bytes",
        "bytesPerSecond": "bytes_per_s",
    }),
    "DRSwitchover": (DRSwitchover, {**_COMMON, "afterAcked": "after_acked"}),
    "WriteStorm": (WriteStorm, {
        **_COMMON, "prefix": "prefix", "keys": "keys",
        "clients": "clients", "txns": "txns", "priority": "priority",
        "openLoop": "open_loop", "arrivalSeconds": "arrival_s",
        "blind": "blind",
    }),
    "SystemProbe": (SystemProbe, {**_COMMON, "lane": "lane"}),
    "BackpressureMonitor": (BackpressureMonitor, {
        **_COMMON, "engageMin": "engage_min",
    }),
    "TagQuotaAbuse": (TagQuotaAbuse, {
        **_COMMON, "tag": "tag", "quota": "quota",
        "clients": "clients", "duration": "duration",
    }),
    "CrossTenantProbe": (CrossTenantProbe, {**_COMMON, "prefix": "prefix"}),
}
