"""End-to-end commit-path observability (ISSUE 12 + ISSUE 15 tentpoles).

Six pieces, one subsystem:

- ``span``: per-transaction commit-path tracing — sampled txns carry a
  trace context through the wire structs, every role stamps span
  boundaries, the client assembles the exact per-stage breakdown and the
  residue is reported as ``unattributed`` (never silently dropped).
- ``registry``: the unified metrics scrape — every role's counters plus
  tracer/span tallies in one namespaced snapshot, emitted as Prometheus
  text, one JSON line, or a periodic JSONL time-series with explicit
  ``scrape_gap`` records for dead/unreachable roles.
- ``recorder``: the cluster flight recorder — an always-on, bounded
  on-disk ring of metric snapshots with first-class event annotations
  on the same timeline (ratekeeper limiting transitions, recovery
  stages, resolver-queue crossings, admission engage/release, chaos
  fault/heal windows, reshard/repack events, scrape gaps).
- ``slo``: rolling-baseline anomaly detection + SLO burn tracking
  (commit p99 / goodput / unknown-result rate) computed incrementally
  from the ring, with warm-up / insufficient-sample honesty flags —
  exported as status JSON ``workload.slo`` and the slo_* counters.
- ``doctor``: deterministic root-cause reports per anomaly window
  (dominant stage + co-occurring annotations + one-line verdict), the
  chaos fault-window attribution table, and the ``--doctor-gate`` CI
  line; ``history`` folds the committed bench artifacts into the
  perf-trajectory table (``--bench-history``).
- ``selfcheck``: the CI face — ``python -m foundationdb_tpu.obs`` runs a
  short sim and verifies span completeness, the reconciliation identity,
  and the scrape audit in one JSON line; ``--ab`` measures the 1-in-64
  sampling overhead AND the recorder-armed overhead against the <=2%
  gate (scripts/obs_ab.sh -> OBS_AB.json).

Knobs (README "Observability"): FDB_TPU_OBS (default 0),
FDB_TPU_OBS_SAMPLE (default 64 — sample 1-in-N transactions),
FDB_TPU_RECORDER (ring path — arms the flight recorder on a server.py
controller process), FDB_TPU_RECORDER_INTERVAL (snapshot seconds,
default 5).
"""

from foundationdb_tpu.obs.registry import (
    CHAOS_DOCUMENTED_COUNTERS,
    DOCUMENTED_COUNTERS,
    RECORDER_DOCUMENTED_COUNTERS,
    MetricsPoller,
    MetricsRegistry,
    add_span_sink,
    scrape_deployed,
    scrape_deployed_async,
    scrape_sim,
)
from foundationdb_tpu.obs.doctor import (
    attribute_faults,
    diagnose,
    run_doctor_gate,
)
from foundationdb_tpu.obs.history import bench_history
from foundationdb_tpu.obs.recorder import (
    ANNOTATION_CLASSES,
    TRACE_CATALOG,
    FlightRecorder,
)
from foundationdb_tpu.obs.slo import SloTracker
from foundationdb_tpu.obs.selfcheck import (
    latency_probe,
    run_overhead_ab,
    run_selfcheck,
)
from foundationdb_tpu.obs.span import (
    READ_STAGES,
    SUB_STAGES,
    TXN_STAGES,
    SpanSink,
    TraceContext,
    check_txn_tree,
    obs_env_default,
    obs_sample_default,
    span_sink,
)

__all__ = [
    "ANNOTATION_CLASSES",
    "CHAOS_DOCUMENTED_COUNTERS",
    "DOCUMENTED_COUNTERS",
    "FlightRecorder",
    "MetricsPoller",
    "MetricsRegistry",
    "READ_STAGES",
    "RECORDER_DOCUMENTED_COUNTERS",
    "SUB_STAGES",
    "SloTracker",
    "SpanSink",
    "TRACE_CATALOG",
    "TXN_STAGES",
    "TraceContext",
    "add_span_sink",
    "attribute_faults",
    "bench_history",
    "check_txn_tree",
    "diagnose",
    "latency_probe",
    "obs_env_default",
    "obs_sample_default",
    "run_doctor_gate",
    "run_overhead_ab",
    "run_selfcheck",
    "scrape_deployed",
    "scrape_deployed_async",
    "scrape_sim",
    "span_sink",
]
