"""End-to-end commit-path observability (ISSUE 12 tentpole).

Three pieces, one subsystem:

- ``span``: per-transaction commit-path tracing — sampled txns carry a
  trace context through the wire structs, every role stamps span
  boundaries, the client assembles the exact per-stage breakdown and the
  residue is reported as ``unattributed`` (never silently dropped).
- ``registry``: the unified metrics scrape — every role's counters plus
  tracer/span tallies in one namespaced snapshot, emitted as Prometheus
  text, one JSON line, or a periodic JSONL time-series.
- ``selfcheck``: the CI face — ``python -m foundationdb_tpu.obs`` runs a
  short sim and verifies span completeness, the reconciliation identity,
  and the scrape audit in one JSON line; ``--ab`` measures the 1-in-64
  sampling overhead against the <=2% gate (scripts/obs_ab.sh ->
  OBS_AB.json).

Knobs (README "Observability"): FDB_TPU_OBS (default 0),
FDB_TPU_OBS_SAMPLE (default 64 — sample 1-in-N transactions).
"""

from foundationdb_tpu.obs.registry import (
    CHAOS_DOCUMENTED_COUNTERS,
    DOCUMENTED_COUNTERS,
    MetricsPoller,
    MetricsRegistry,
    scrape_deployed,
    scrape_sim,
)
from foundationdb_tpu.obs.selfcheck import (
    latency_probe,
    run_overhead_ab,
    run_selfcheck,
)
from foundationdb_tpu.obs.span import (
    SUB_STAGES,
    TXN_STAGES,
    SpanSink,
    TraceContext,
    check_txn_tree,
    obs_env_default,
    obs_sample_default,
    span_sink,
)

__all__ = [
    "CHAOS_DOCUMENTED_COUNTERS",
    "DOCUMENTED_COUNTERS",
    "MetricsPoller",
    "MetricsRegistry",
    "SUB_STAGES",
    "SpanSink",
    "TXN_STAGES",
    "TraceContext",
    "check_txn_tree",
    "latency_probe",
    "obs_env_default",
    "obs_sample_default",
    "run_overhead_ab",
    "run_selfcheck",
    "scrape_deployed",
    "scrape_sim",
    "span_sink",
]
