"""MetricsRegistry: one namespaced scrape of every role's counters.

Every role already exports counters (``get_metrics`` / ``metrics`` /
``get_rates``), but each surface had its own consumer — status JSON reads
a hand-picked subset, the ratekeeper another, the benches a third. The
registry is the single scrape: every role instance's metrics flattened
into ``<role>.<instance>.<metric>`` keys (numbers and booleans only — the
scrape is a metrics plane, not an object dump), plus the tracer's event
counts and the span sink's tallies, emitted as

- Prometheus text exposition (``to_prometheus``): one gauge per metric,
  ``process`` label per instance, ``fdb_tpu_`` prefix;
- one JSON line (``to_json_line``): the CI/tooling form every A/B script
  in this repo already parses;
- a periodic JSONL time-series (``MetricsPoller``): deployed clusters
  append one snapshot per interval for offline dashboards.

The name audit (``audit``) is the registry's hygiene contract, pinned by
tests: every metric leaf is snake_case, and no two sources collide on a
full namespaced key (a collision would silently overwrite one role's
truth with another's).
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")

#: status-JSON / README counters that MUST exist in a full-cluster scrape
#: (the metrics-name audit pins these: a rename that orphans a documented
#: counter fails the battery, not a user's dashboard).
DOCUMENTED_COUNTERS = (
    "grv_proxy.grvs_served",
    "grv_proxy.queued",
    "grv_proxy.tag_throttled",
    "grv_proxy.admission_defer_ticks",
    "commit_proxy.txns_committed",
    "commit_proxy.txns_conflicted",
    "commit_proxy.conflict_losses",
    "resolver.batches_resolved",
    "resolver.txns_resolved",
    "resolver.txns_conflicted",
    "resolver.txns_reordered",
    "resolver.txns_cycle_aborted",
    "resolver.wave_batches",
    "commit_proxy.wave_exchanges",
    "resolver.txns_rejected_fail_safe",
    "resolver.overflow_events",
    # Speculative pipelined resolve (FDB_TPU_SPEC_RESOLVE): exported
    # unconditionally (zeros on serial engines) so dashboards can alert
    # on the mis-speculation rate (repaired/dispatched) without a flag
    # check, and the ratekeeper's depth clamp is auditable from the
    # scrape alone.
    "resolver.spec_dispatched",
    "resolver.spec_confirmed",
    "resolver.spec_repaired",
    "resolver.spec_depth",
    "resolver.chain_rolls",
    "resolver.queue.depth",
    # Tiered-dictionary economics (FDB_TPU_DICT_HOT_CAPACITY): exported
    # unconditionally by Resolver.get_metrics (zeros when tiering is off
    # or the engine is not resident) so the doctor's dict_thrash detector
    # and dashboards read one stable namespace.
    "resolver.engine.demotions",
    "resolver.engine.promotions",
    "resolver.engine.cold_tier_keys",
    "resolver.engine.dict_hot_occupancy",
    "resolver.engine.demotion_bytes_per_dispatch",
    "tlog.queue_bytes",
    "tlog.queue_entries",
    "storage.version_lag",
    # Read plane + watch registry (foundationdb_tpu/reads/): exported by
    # every storage server, zeros while idle, so a healthy scrape always
    # carries them.
    "storage.watch_count",
    "storage.too_many_watches",
    "storage.watch_fires",
    "storage.reads.dispatches",
    "storage.reads.served",
    "storage.reads.queue_depth",
    "storage.reads.occupancy",
    "storage.reads.per_dispatch",
    "ratekeeper.tps_limit",
    # Recovery MTTR counters (deployed chaos subsystem): exported by BOTH
    # controllers — runtime/cluster.py (sim) and server.py
    # DeployedController — under identical names, zeros before the first
    # recovery (so the audit holds on a healthy cluster too).
    "controller.recovery_count",
    "controller.recovery_lock_s",
    "controller.recovery_salvage_s",
    "controller.recovery_recruit_s",
    "controller.recovery_total_s",
)

#: counters the deployed chaos harness (loadgen/chaos.py) contributes to
#: ITS scrape under the `chaos` role — documented/pinned like the core
#: set, but only expected in chaos-run scrapes (a plain cluster has no
#: chaos harness to export them), so they ride `missing_documented`'s
#: `extra` parameter instead of the always-on tuple.
CHAOS_DOCUMENTED_COUNTERS = (
    "chaos.chaos_faults_injected",
    "chaos.chaos_kills",
    "chaos.chaos_restarts",
    "chaos.chaos_partitions",
    "chaos.chaos_heals",
    "chaos.chaos_pauses",
)

#: counters the flight recorder + SLO tracker (obs/recorder.py, obs/slo.py)
#: contribute to a RECORDER-ARMED scrape — documented/pinned like the
#: chaos set, expected only when a recorder rides the scrape (the doctor
#: gate and recorder selfchecks pass them via `missing_documented(extra=)`).
RECORDER_DOCUMENTED_COUNTERS = (
    "recorder.recorder_snapshots",
    "recorder.recorder_annotations",
    "recorder.recorder_scrape_gaps",
    "recorder.recorder_compactions",
    "recorder.recorder_ring_records",
    "slo.slo_windows",
    "slo.slo_anomaly_windows",
    "slo.slo_incidents",
    "slo.slo_burn_violations",
    "slo.slo_insufficient_windows",
    "slo.slo_warmed_up",
)

#: counters the elastic autoscaler (autoscale/) contributes to a scrape
#: when its control loop is ARMED — scoped like the chaos/recorder sets
#: (a plain cluster has no autoscaler riding the scrape), so autoscale
#: runs pass them via `missing_documented(extra=)`.
AUTOSCALE_DOCUMENTED_COUNTERS = (
    "autoscale.autoscale_windows_observed",
    "autoscale.autoscale_scale_ups",
    "autoscale.autoscale_scale_downs",
    "autoscale.autoscale_suppressed_cooldown",
    "autoscale.autoscale_suppressed_confirm",
    "autoscale.autoscale_suppressed_bounds",
    "autoscale.autoscale_events_total",
)


def _flatten(out: dict, prefix: str, value: Any) -> None:
    """Numbers and booleans keep their key; dicts recurse with dots;
    everything else (strings, lists — e.g. hot_ranges tables) is not a
    metric and is dropped from the scrape."""
    if isinstance(value, bool):
        out[prefix] = int(value)
    elif isinstance(value, (int, float)):
        out[prefix] = value
    elif isinstance(value, dict):
        for k, v in value.items():
            _flatten(out, f"{prefix}.{k}", v)


class MetricsRegistry:
    """Collects (role, instance, metrics-dict) tuples into one snapshot."""

    def __init__(self) -> None:
        # full key -> value; plus the collision log the audit reports.
        self.values: dict[str, float] = {}
        self.collisions: list[str] = []
        self._sources: dict[str, int] = {}  # full key -> add() call seq
        self._add_seq = 0
        # Probes that FAILED this scrape: a dead/unreachable role is an
        # explicit (role, instance, reason) gap record, never a silent
        # hole — MetricsPoller/FlightRecorder turn these into scrape_gap
        # timeline records with the outage duration attached. sources_ok
        # is the complement (who DID answer), for outage-duration
        # bookkeeping across snapshots.
        self.gaps: list[dict] = []
        self.sources_ok: list[tuple[str, str]] = []

    def note_gap(self, role: str, instance: str, reason: str) -> None:
        self.gaps.append(
            {"role": role, "instance": instance, "reason": reason})

    def add(self, role: str, instance: str, metrics: "dict | None") -> None:
        if not metrics:
            return
        self.sources_ok.append((role, instance))
        self._add_seq += 1
        flat: dict[str, float] = {}
        _flatten(flat, role, metrics)
        for key, v in flat.items():
            full = f"{key}#{instance}" if instance else key
            if full in self.values and self._sources[full] != self._add_seq:
                # Two distinct sources produced the SAME namespaced key —
                # one role's truth silently overwrote another's (e.g. two
                # endpoints scraped under one instance name).
                self.collisions.append(full)
            self.values[full] = v
            self._sources[full] = self._add_seq

    def snapshot(self) -> dict:
        """{namespaced key (instance suffix stripped where unique) ->
        value} with per-instance values under ``key#instance``."""
        return dict(sorted(self.values.items()))

    def aggregated(self) -> dict:
        """Instance-summed view ``<role>.<metric> -> value`` (counters
        sum across instances — the status-JSON convention)."""
        agg: dict[str, float] = {}
        for full, v in self.values.items():
            key = full.split("#", 1)[0]
            agg[key] = agg.get(key, 0) + v
        return dict(sorted(agg.items()))

    # -- hygiene -------------------------------------------------------------

    def audit(self) -> list[str]:
        """Name-hygiene problems: non-snake_case leaves, and full-key
        collisions between distinct sources. Empty == clean.

        The ``trace.events.*`` namespace is exempt from the snake_case
        rule: its leaves are TraceEvent TYPE names, which are CamelCase
        by the reference's convention (MasterRecoveryTriggered, ...) —
        they are labels riding the scrape, not metric names."""
        problems = [f"collision: {k}" for k in self.collisions]
        for full in self.values:
            key = full.split("#", 1)[0]
            if key.startswith("trace.events."):
                continue
            for leaf in key.split("."):
                if not _SNAKE.match(leaf):
                    problems.append(f"not snake_case: {full} (leaf {leaf!r})")
                    break
        return problems

    def missing_documented(self, extra: tuple = ()) -> list[str]:
        """Documented counters absent from this scrape (prefix match on
        the aggregated keys). `extra`: additional documented names this
        scrape's scope must also carry (e.g. CHAOS_DOCUMENTED_COUNTERS
        for a chaos-run scrape)."""
        agg = self.aggregated()
        return [c for c in DOCUMENTED_COUNTERS + tuple(extra)
                if c not in agg]

    # -- emission ------------------------------------------------------------

    @staticmethod
    def _prom_name(key: str) -> str:
        return "fdb_tpu_" + re.sub(r"[^a-zA-Z0-9_]", "_", key)

    def to_prometheus(self) -> str:
        """Prometheus text exposition: one gauge per metric key, the
        instance as a ``process`` label."""
        by_name: dict[str, list[tuple[str, float]]] = {}
        for full, v in self.values.items():
            key, _, inst = full.partition("#")
            by_name.setdefault(self._prom_name(key), []).append((inst, v))
        lines = []
        for name in sorted(by_name):
            lines.append(f"# TYPE {name} gauge")
            for inst, v in sorted(by_name[name]):
                label = f'{{process="{inst}"}}' if inst else ""
                lines.append(f"{name}{label} {v}")
        return "\n".join(lines) + "\n"

    def to_json_line(self, **extra) -> str:
        doc = {"metric": "obs_scrape", **extra,
               "metrics": self.aggregated()}
        return json.dumps(doc, sort_keys=True)


def add_span_sink(reg: MetricsRegistry, sink) -> None:
    """Contribute a SpanSink's tallies + timeline counters to a scrape
    (the ``obs`` role): cumulative per-stage sum/count and the raw e2e
    histogram bins. Cumulative-counter form on purpose — the flight
    recorder's consumers (obs/slo.py, obs/doctor.py) diff CONSECUTIVE
    snapshots into per-window histograms, which is the only honest way
    to quote an interval p99 from a running sink."""
    b = sink.breakdown()
    reg.add("obs", "", {
        "txns_seen": b["txns_seen"],
        "txns_sampled": b["txns_sampled"],
        "spans": len(sink.spans),
        "unattributed_ms": b["unattributed_ms"],
        "stage_sum_ms": {
            name: round(h.sum_ms, 4)
            for name, h in sorted(sink.stage_hists.items())
        },
        "stage_count": {
            name: h.count for name, h in sorted(sink.stage_hists.items())
        },
        "e2e_sum_ms": round(sink.e2e_hist.sum_ms, 4),
        "e2e_count": sink.e2e_hist.count,
        "e2e_bins": {
            f"b{i}": n for i, n in sink.e2e_hist.to_dict()["bins"]
        },
    })


async def scrape_sim(cluster) -> MetricsRegistry:
    """Scrape every role of a SimCluster over its simulated network (the
    status-JSON discipline: an unreachable role's counters are genuinely
    invisible, never read in-process — but never a silent hole either:
    a failed probe is an explicit reg.gaps entry), plus tracer event
    counts and the span sink's tallies."""
    reg = MetricsRegistry()
    spawn = cluster.loop.spawn

    async def safe(fut):
        try:
            return await fut
        except Exception as e:
            return e

    probes: list[tuple[str, str, Any]] = []

    def probe(role: str, ep, coro) -> None:
        probes.append((role, ep.process,
                       spawn(safe(coro), name=f"obs.scrape.{ep.process}")))

    for ep in cluster.grv_proxy_eps:
        probe("grv_proxy", ep, ep.get_metrics())
    for ep in cluster.commit_proxy_eps:
        probe("commit_proxy", ep, ep.get_metrics())
    for ep in cluster.resolver_eps:
        probe("resolver", ep, ep.get_metrics())
    for ep in cluster.tlog_eps:
        probe("tlog", ep, ep.metrics())
    for ep in cluster.storage_eps:
        probe("storage", ep, ep.metrics())
    if cluster.ratekeeper_ep is not None:
        probe("ratekeeper", cluster.ratekeeper_ep,
              cluster.ratekeeper_ep.get_rates())
    ctrl_ep = getattr(cluster, "controller_ep", None)
    if ctrl_ep is not None:
        probe("controller", ctrl_ep, ctrl_ep.get_metrics())
    # Autoscaler rides the scrape in-process when armed (control loop,
    # not a cluster role — it has no endpoint of its own).
    scaler = getattr(cluster, "autoscaler", None)
    if scaler is not None:
        reg.add("autoscale", "", scaler.metrics())
    for role, inst, task in probes:
        m = await task
        if isinstance(m, BaseException):
            reg.note_gap(role, inst, type(m).__name__)
        else:
            reg.add(role, inst, m)

    tracer = getattr(cluster.loop, "tracer", None)
    if tracer is not None:
        reg.add("trace", "", {"events": dict(tracer.counts)})
    sink = getattr(cluster.loop, "span_sink", None)
    if sink is not None:
        add_span_sink(reg, sink)
    return reg


def _deployed_plans(spec: dict) -> list[tuple[str, str, str, str]]:
    plans: list[tuple[str, str, str, str]] = []
    for role, service, method in (
        ("proxy", "grv_proxy", "get_metrics"),
        ("proxy", "commit_proxy", "get_metrics"),
        ("resolver", "resolver", "get_metrics"),
        ("tlog", "tlog", "metrics"),
        ("storage", "storage", "metrics"),
        ("ratekeeper", "ratekeeper", "get_rates"),
        ("controller", "controller", "get_metrics"),
    ):
        for i, addr in enumerate(spec.get(role) or []):
            plans.append((service, f"{service}{i}", addr, method))
    return plans


async def scrape_deployed_async(loop, t, spec: dict,
                                timeout_s: float = 5.0) -> MetricsRegistry:
    """Async deployed scrape: awaitable from INSIDE a running RealLoop
    (the flight recorder's periodic task), probe RPCs time-bounded AND
    concurrent — k black-holed roles cost ONE timeout for the whole
    sweep, not k serial ones, so the recorder's snapshot cadence holds
    through exactly the outages it exists to record."""
    from foundationdb_tpu.server import bounded_rpc, parse_addr

    reg = MetricsRegistry()

    async def probe(service, inst, addr, method):
        ep = t.endpoint(parse_addr(addr), service)
        try:
            return await bounded_rpc(loop, getattr(ep, method)(),
                                     timeout_s, transport=t)
        except Exception as e:  # noqa: BLE001 — a gap record, not a crash
            return e

    plans = _deployed_plans(spec)
    tasks = [loop.spawn(probe(*plan), name=f"obs.scrape.{plan[1]}")
             for plan in plans]
    for (service, inst, _addr, _method), task in zip(plans, tasks):
        m = await task
        if isinstance(m, BaseException):
            reg.note_gap(service, inst, type(m).__name__)
        else:
            reg.add(service, inst, m)
    return reg


def scrape_deployed(loop, t, spec: dict) -> MetricsRegistry:
    """Scrape a deployed cluster over its TCP endpoints (the cli
    ``status`` role table, registry-shaped). Synchronous driver: pumps
    the caller's RealLoop like cli.Shell does; the probe plan and gap
    accounting are scrape_deployed_async's."""
    return loop.run(scrape_deployed_async(loop, t, spec), timeout=120.0)


def scrape_gap_records(reg: MetricsRegistry, t: float,
                       last_ok: dict, armed_at: float) -> list[dict]:
    """THE outage-duration bookkeeping, shared by every scrape-loop
    surface (MetricsPoller.run, the --poll CLI drive, the
    FlightRecorder): update the last-answered stamp of every source
    that DID reply this scrape, then turn each failed probe into one
    scrape_gap record carrying how long that instance has been dark
    (since its last answer, or since the scraper armed)."""
    for src in reg.sources_ok:
        last_ok[src] = t
    out = []
    for g in reg.gaps:
        key = (g["role"], g["instance"])
        since = last_ok.get(key, armed_at)
        out.append({
            "metric": "scrape_gap",
            "t": round(t, 3),
            "role": g["role"],
            "instance": g["instance"],
            "reason": g["reason"],
            "duration_s": round(t - since, 3),
        })
    return out


class MetricsPoller:
    """Periodic JSONL time-series: append one aggregated snapshot per
    interval — the deployed-cluster "scrape loop" (point Prometheus at
    to_prometheus for pull; this is the push/file form for hosts without
    a scraper).

    A dead/unreachable role is never a silent hole in the series: every
    failed probe becomes an explicit ``scrape_gap`` record on the same
    timeline — (role, instance, reason, duration since that instance
    last answered), one per affected probe per snapshot while the outage
    lasts — so an offline reader can tell "role was down" from "poller
    never looked"."""

    def __init__(self, loop, scrape: Callable, path: str,
                 interval_s: float = 5.0):
        self.loop = loop
        self.scrape = scrape  # async () -> MetricsRegistry
        self.path = path
        self.interval_s = interval_s
        self.snapshots_written = 0
        self.gaps_written = 0
        self._armed_at = loop.now
        self._last_ok: dict[tuple, float] = {}  # (role, inst) -> last t

    def gap_records(self, reg: MetricsRegistry, t: float) -> list[dict]:
        """Turn one scrape's probe failures into timeline records (the
        shared scrape_gap_records bookkeeping)."""
        return scrape_gap_records(reg, t, self._last_ok, self._armed_at)

    async def run(self) -> None:
        while True:
            await self.loop.sleep(self.interval_s)
            reg = await self.scrape()
            now = self.loop.now
            lines = [json.dumps(r, sort_keys=True)
                     for r in self.gap_records(reg, now)]
            self.gaps_written += len(lines)
            lines.append(reg.to_json_line(
                t=round(now, 3), seq=self.snapshots_written))
            with open(self.path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
            self.snapshots_written += 1
