"""SLO burn tracking + rolling-baseline anomaly detection (obs subsystem).

Consumes the flight recorder's snapshot stream (obs/recorder.py)
INCREMENTALLY: each pair of consecutive metric snapshots defines one
*window*, and every SLI is computed from counter deltas over that window
— never from cumulative run totals, which average an incident away:

- ``goodput_tps``      Δ commit_proxy.txns_committed / Δt
- ``commit_p99_ms``    p99 of the window's e2e latency histogram,
                       obtained by DIFFING the sink's cumulative
                       log-binned histogram between the two snapshots
                       (obs.e2e_bins.* — the only honest interval p99 a
                       running sink admits); quotable only at
                       >= MIN_P99_SAMPLES samples in the window
- ``unknown_frac``     Δ client.commit_unknowns / Δ client-side commit
                       outcomes, when a client-side harness (chaos,
                       open-loop) contributes the ``client`` role;
                       quotable only at >= MIN_UNKNOWN_OUTCOMES outcomes
                       in the window

Honesty is structural, not advisory: every window carries
``p99_quotable``; no anomaly is ever claimed before WARMUP_WINDOWS
baseline windows exist (``warmed_up`` rides status JSON and the slo_*
counters); insufficient-sample windows are counted, not silently used.

Anomaly rule (per SLI): the window value must deviate from the rolling
baseline mean by BOTH k·σ and a relative guard (σ of a quiet sim is ~0,
so k·σ alone would fire on noise; the relative guard alone would miss
slow degradations on noisy hosts). Baselines accumulate only
NON-anomalous windows so an incident cannot poison the reference it is
judged against. Contiguous anomalous windows merge into one *incident*
— the unit obs/doctor.py attributes a root cause to.

SLO burn: each objective (absolute bound, e.g. commit p99 <= 500ms) is
checked per window; the burn rate is the violating-window fraction over
the configured error budget (burn_rate > 1 == burning hotter than the
budget allows). Exported into status JSON ``workload.slo`` and, via
``metrics()``, as the documented ``slo_*`` counters on the Prometheus /
registry plane.
"""

from __future__ import annotations

from collections import deque

from foundationdb_tpu.loadgen.harness import LatencyHistogram

#: default absolute objectives (override per deployment via the recorder);
#: None disables an objective. goodput has no universal floor — its SLO
#: is the relative anomaly path unless the operator supplies one.
DEFAULT_OBJECTIVES = {
    "commit_p99_ms": 1000.0,
    "goodput_min_tps": None,
    "unknown_frac_max": 0.01,
}


def p99_from_bins(bins: "dict[int, int]", q: float = 99.0) -> float:
    """Percentile over a sparse {bin index: count} histogram in
    LatencyHistogram's shared bin space (conservative upper-edge rule,
    same as LatencyHistogram.percentile; overflow bin reports the top
    edge — the diffed interval histogram has no exact max)."""
    total = sum(bins.values())
    if total <= 0:
        return 0.0
    target = -(-total * q // 100)  # ceil
    edges = LatencyHistogram._EDGES
    cum = 0
    for i in sorted(bins):
        cum += bins[i]
        if cum >= target:
            if i >= len(edges):
                return round(float(edges[-1]), 4)
            return round(float(edges[i]), 4)
    return round(float(edges[-1]), 4)


class SloTracker:
    #: baseline windows required before ANY anomaly may be claimed.
    WARMUP_WINDOWS = 5
    #: rolling baseline length (non-anomalous windows).
    BASELINE_WINDOW = 60
    #: e2e samples a window needs for its p99 to be quotable.
    MIN_P99_SAMPLES = 20
    #: client-side outcomes a window needs for its unknown-result rate
    #: to be quotable (1 unknown among 3 outcomes is 33% by arithmetic
    #: and noise by any honest reading).
    MIN_UNKNOWN_OUTCOMES = 20
    #: k·σ deviation gate.
    K_SIGMA = 4.0
    #: relative guards: goodput must fall below (1-0.5)·mean, p99 must
    #: exceed (1+1.0)·mean — BOTH this and k·σ must hold.
    REL_GOODPUT = 0.5
    REL_P99 = 1.0
    #: SLO error budget: tolerated violating-window fraction.
    ERROR_BUDGET_FRAC = 0.01
    #: bounded memories (long soaks must not grow state).
    MAX_INCIDENTS = 64
    MAX_WINDOWS = 512

    def __init__(self, objectives: "dict | None" = None):
        self.objectives = dict(DEFAULT_OBJECTIVES)
        if objectives:
            self.objectives.update(objectives)
        self._prev: "tuple[float, dict] | None" = None  # (t, aggregated)
        self._baseline: dict[str, deque] = {
            "goodput_tps": deque(maxlen=self.BASELINE_WINDOW),
            "commit_p99_ms": deque(maxlen=self.BASELINE_WINDOW),
        }
        self.windows: deque[dict] = deque(maxlen=self.MAX_WINDOWS)
        self.incidents: list[dict] = []
        self._open_incidents: dict[str, dict] = {}  # sli -> incident
        self.counters = {
            "slo_windows": 0,
            "slo_anomaly_windows": 0,
            "slo_incidents": 0,
            "slo_burn_violations": 0,
            "slo_insufficient_windows": 0,
            "slo_warmed_up": 0,
        }
        self._burn: dict[str, dict] = {}  # objective -> {violating, windows}

    # -- baseline helpers ------------------------------------------------------

    @staticmethod
    def _mean_std(values) -> tuple[float, float]:
        n = len(values)
        if n == 0:
            return 0.0, 0.0
        mean = sum(values) / n
        var = sum((v - mean) ** 2 for v in values) / n
        return mean, var ** 0.5

    @property
    def warmed_up(self) -> bool:
        return len(self._baseline["goodput_tps"]) >= self.WARMUP_WINDOWS

    # -- ingest ----------------------------------------------------------------

    @staticmethod
    def _e2e_bins(agg: dict) -> "dict[int, int]":
        pref = "obs.e2e_bins.b"
        return {int(k[len(pref):]): int(v) for k, v in agg.items()
                if k.startswith(pref)}

    def observe(self, t: float, agg: dict) -> list[dict]:
        """One snapshot's aggregated metrics. Returns the anomaly
        annotations this window OPENED (the recorder rings them onto the
        timeline); window/burn/incident state updates internally."""
        prev = self._prev
        self._prev = (t, dict(agg))
        if prev is None:
            return []
        t0, agg0 = prev
        dt = t - t0
        if dt <= 0:
            return []
        self.counters["slo_windows"] += 1

        win: dict = {"t0": round(t0, 3), "t1": round(t, 3),
                     "dt_s": round(dt, 3)}
        # goodput from the committed-txn counter delta (re-baselined on
        # counter regression — a recovery swapped the proxy generation).
        c0 = agg0.get("commit_proxy.txns_committed", 0)
        c1 = agg.get("commit_proxy.txns_committed", 0)
        win["goodput_tps"] = round(max(0, c1 - c0) / dt, 2)
        # interval p99 from the cumulative e2e histogram diff.
        b0, b1 = self._e2e_bins(agg0), self._e2e_bins(agg)
        dbins = {i: n - b0.get(i, 0) for i, n in b1.items()
                 if n - b0.get(i, 0) > 0}
        n_samples = sum(dbins.values())
        win["e2e_samples"] = n_samples
        win["p99_quotable"] = n_samples >= self.MIN_P99_SAMPLES
        win["commit_p99_ms"] = (p99_from_bins(dbins)
                                if win["p99_quotable"] else None)
        if not win["p99_quotable"]:
            self.counters["slo_insufficient_windows"] += 1
        # unknown-result rate, when a client-side harness reports it —
        # quotable only at MIN_UNKNOWN_OUTCOMES outcomes in the window
        # (below the floor the SLI is None, mirroring p99_quotable, and
        # neither the anomaly path nor burn accounting consumes it).
        u0, u1 = (agg0.get("client.commit_unknowns"),
                  agg.get("client.commit_unknowns"))
        if u0 is not None and u1 is not None:
            a0 = agg0.get("client.commits_acked", 0)
            a1 = agg.get("client.commits_acked", 0)
            outcomes = max(0, (u1 - u0)) + max(0, (a1 - a0))
            win["client_outcomes"] = outcomes
            win["unknown_frac"] = (
                round(max(0, u1 - u0) / outcomes, 4)
                if outcomes >= self.MIN_UNKNOWN_OUTCOMES else None)
        else:
            win["client_outcomes"] = None
            win["unknown_frac"] = None

        anomalies = self._judge(win)
        win["anomalous"] = sorted(anomalies)
        self.windows.append(win)
        self._account_burn(win)
        return self._update_incidents(win, anomalies)

    # -- anomaly judgement -----------------------------------------------------

    def _judge(self, win: dict) -> dict[str, dict]:
        """SLI -> {observed, baseline_mean} for every SLI anomalous in
        this window. Never fires before warm-up; only non-anomalous
        values feed the baselines."""
        out: dict[str, dict] = {}
        warmed = self.warmed_up
        self.counters["slo_warmed_up"] = int(warmed)

        g = win["goodput_tps"]
        mean, std = self._mean_std(self._baseline["goodput_tps"])
        if (warmed and g < mean * (1 - self.REL_GOODPUT)
                and g < mean - self.K_SIGMA * std):
            out["goodput_tps"] = {"observed": g,
                                  "baseline_mean": round(mean, 2)}
        else:
            self._baseline["goodput_tps"].append(g)

        if win["p99_quotable"]:
            p = win["commit_p99_ms"]
            bl = self._baseline["commit_p99_ms"]
            mean, std = self._mean_std(bl)
            if (warmed and len(bl) >= self.WARMUP_WINDOWS
                    and p > mean * (1 + self.REL_P99)
                    and p > mean + self.K_SIGMA * std):
                out["commit_p99_ms"] = {"observed": p,
                                        "baseline_mean": round(mean, 3)}
            else:
                bl.append(p)

        u = win["unknown_frac"]
        bound = self.objectives.get("unknown_frac_max")
        if warmed and u is not None and bound is not None and u > bound:
            # Absolute bound, but the warm-up gate still applies: "no
            # anomaly before WARMUP_WINDOWS" is the module's structural
            # promise, for every SLI.
            out["unknown_frac"] = {"observed": u, "baseline_mean": bound}
        return out

    # -- burn ------------------------------------------------------------------

    def _account_burn(self, win: dict) -> None:
        checks = []
        bound = self.objectives.get("commit_p99_ms")
        if bound is not None and win["p99_quotable"]:
            checks.append(("commit_p99_ms", win["commit_p99_ms"] > bound))
        floor = self.objectives.get("goodput_min_tps")
        if floor is not None:
            checks.append(("goodput_min_tps", win["goodput_tps"] < floor))
        cap = self.objectives.get("unknown_frac_max")
        if cap is not None and win["unknown_frac"] is not None:
            checks.append(("unknown_frac_max", win["unknown_frac"] > cap))
        for name, violated in checks:
            b = self._burn.setdefault(name, {"violating": 0, "windows": 0})
            b["windows"] += 1
            if violated:
                b["violating"] += 1
                self.counters["slo_burn_violations"] += 1

    # -- incidents -------------------------------------------------------------

    def _update_incidents(self, win: dict,
                          anomalies: dict[str, dict]) -> list[dict]:
        """Merge contiguous anomalous windows into incidents; returns
        annotation payloads for NEWLY opened incidents."""
        opened: list[dict] = []
        if anomalies:
            self.counters["slo_anomaly_windows"] += 1
        for sli, info in anomalies.items():
            inc = self._open_incidents.get(sli)
            if inc is None:
                inc = {"sli": sli, "t0": win["t0"], "t1": win["t1"],
                       "observed": info["observed"],
                       "baseline_mean": info["baseline_mean"],
                       "windows": 1}
                self._open_incidents[sli] = inc
                self.incidents.append(inc)
                del self.incidents[:-self.MAX_INCIDENTS]
                self.counters["slo_incidents"] += 1
                opened.append({"name": "SloAnomalyDetected", "sli": sli,
                               **info, "t0": win["t0"]})
            else:
                inc["t1"] = win["t1"]
                inc["windows"] += 1
                # Keep the WORST observation as the incident headline.
                worse = (info["observed"] < inc["observed"]
                         if sli == "goodput_tps"
                         else info["observed"] > inc["observed"])
                if worse:
                    inc["observed"] = info["observed"]
        for sli in list(self._open_incidents):
            if sli not in anomalies:
                del self._open_incidents[sli]  # incident closed
        return opened

    # -- export ----------------------------------------------------------------

    def status(self) -> dict:
        """The ``workload.slo`` status-JSON document (honesty flags are
        first-class: warm-up state, per-window p99 quotability, and the
        insufficient-sample count are always present)."""
        last = self.windows[-1] if self.windows else None
        burn = {}
        for name, b in self._burn.items():
            frac = b["violating"] / b["windows"] if b["windows"] else 0.0
            burn[name] = {
                "objective": self.objectives.get(name),
                "windows": b["windows"],
                "violating": b["violating"],
                "violating_frac": round(frac, 4),
                "budget_frac": self.ERROR_BUDGET_FRAC,
                "burn_rate": round(frac / self.ERROR_BUDGET_FRAC, 2),
            }
        return {
            "enabled": True,
            "warmed_up": self.warmed_up,
            "warmup_windows": self.WARMUP_WINDOWS,
            "windows": self.counters["slo_windows"],
            "anomaly_windows": self.counters["slo_anomaly_windows"],
            "insufficient_p99_windows":
                self.counters["slo_insufficient_windows"],
            "current": last,
            "objectives": dict(self.objectives),
            "burn": burn,
            "incidents": self.incidents[-8:],
            "open_incidents": sorted(self._open_incidents),
        }

    def metrics(self) -> dict:
        """The documented slo_* counters (registry/Prometheus plane)."""
        return dict(self.counters)
