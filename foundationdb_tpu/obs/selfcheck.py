"""obs self-check + sampling-overhead A/B (the CI face of the subsystem).

``run_selfcheck()`` boots a small sim cluster with tracing armed, drives a
deterministic closed-loop workload, and verifies the subsystem's whole
contract in one pass:

- every sampled transaction's span tree is COMPLETE (no stage gaps) and
  satisfies the reconciliation identity e2e == sum(stages) + unattributed;
- the population breakdown's residue is bounded (`unattributed_frac`);
- the unified metrics scrape covers every role, passes the snake_case /
  collision audit, and contains every documented counter;
- same seed -> byte-identical span records (the sim determinism gate).

``run_overhead_ab()`` is the off-by-default-cheap gate: the SAME workload
wall-clocked with tracing off vs 1-in-64 sampling, alternating arms,
best-of-N per arm (the standard noise discipline), recording the
throughput overhead against the <=2% acceptance with the repo's honesty
flags. CPU-only sim by design — no TPU claimed (`cpu_fallback: false`
means exactly that, as in the open-loop record).
"""

from __future__ import annotations

import json
import os
import time

from foundationdb_tpu.obs.span import TXN_STAGES, check_txn_tree

#: acceptance gate: throughput overhead of 1-in-64 sampling vs tracing off
OVERHEAD_GATE = 0.02


def _drive(cluster, n_txns: int, n_clients: int = 8,
           conflicting: bool = False) -> None:
    """Deterministic closed-loop workload: `n_clients` client actors,
    each running its share of read-modify-write txns over a small
    keyspace (`conflicting=True` narrows it so the admission/shaped
    paths light up)."""
    from foundationdb_tpu.client.ryw import open_database

    db = open_database(cluster)
    loop = cluster.loop
    n_keys = 4 if conflicting else 64
    done = []

    async def client(c: int) -> None:
        for k in range(n_txns // n_clients):
            key = b"obs/%d" % ((c * 31 + k) % n_keys)

            async def body(tr, key=key):
                v = await tr.get(key)
                tr.set(key, b"%d" % (int(v or b"0") + 1))

            await db.run(body)
        done.append(c)

    async def scenario():
        tasks = [loop.spawn(client(c), name=f"obs.client{c}")
                 for c in range(n_clients)]
        for t in tasks:
            await t

    loop.run(scenario(), timeout=3600)
    assert len(done) == n_clients


def _new_cluster(seed: int, obs: bool, sample_every: int,
                 admission: bool = False,
                 recorder_path: "str | None" = None,
                 recorder_interval_s: "float | None" = None):
    from foundationdb_tpu.sim.cluster import SimCluster

    return SimCluster(seed=seed, n_storages=2, engine="oracle", obs=obs,
                      obs_sample_every=sample_every, admission=admission,
                      recorder_path=recorder_path,
                      recorder_interval_s=recorder_interval_s)


def run_selfcheck(seed: int = 7, txns: int = 192, sample_every: int = 4,
                  max_unattributed_frac: float = 0.10,
                  export_trace: "str | None" = None) -> dict:
    """One-JSON-line self-check record (metric ``obs_selfcheck``).
    ``export_trace``: also write THIS run's sampled window as a
    Chrome-trace/Perfetto timeline — the exported file is literally the
    checked run, not a same-seed replay. The flight recorder rides the
    checked run too (tmp ring, 50ms SIM cadence — a short sim run spans
    well under a wall second of simulated time, so the deployment-default
    5s would never tick): snapshots + SLO windows must materialize and
    ``workload.slo`` must reach status JSON with its honesty flags."""
    import json as _json
    import os as _os
    import tempfile as _tempfile

    from foundationdb_tpu.obs.registry import scrape_sim
    from foundationdb_tpu.runtime.status import fetch_status

    ring_fd, ring_path = _tempfile.mkstemp(prefix="obs_ring_",
                                           suffix=".jsonl")
    _os.close(ring_fd)
    c = _new_cluster(seed, obs=True, sample_every=sample_every,
                     recorder_path=ring_path, recorder_interval_s=0.05)
    _drive(c, txns)
    sink = c.loop.span_sink
    if export_trace:
        with open(export_trace, "w", encoding="utf-8") as f:
            _json.dump(sink.to_chrome_trace(), f)
    problems: list[str] = []

    tids = sink.sampled_tids(complete_only=True)
    committed_trees = 0
    for tid in tids:
        spans = sink.spans_for(tid)
        if not any(s["name"] == "e2e" for s in spans):
            continue  # sampled but never committed in the window
        committed_trees += 1
        problems += [f"tid {tid:#x}: {p}" for p in check_txn_tree(spans)]
    if not committed_trees:
        problems.append("no committed sampled txn produced a span tree")

    b = sink.breakdown()
    if b["unattributed_frac"] > max_unattributed_frac:
        problems.append(
            f"unattributed_frac {b['unattributed_frac']} > "
            f"{max_unattributed_frac}")
    missing_stages = [s for s in TXN_STAGES
                      if s != "shaped_park" and s not in b["stages"]]
    if missing_stages:
        problems.append(f"stages absent from breakdown: {missing_stages}")

    reg = c.loop.run(scrape_sim(c), timeout=600)
    problems += reg.audit()
    missing = reg.missing_documented()
    if missing:
        problems.append(f"documented counters missing from scrape: {missing}")

    status = c.loop.run(fetch_status(c), timeout=600)
    lb = status["workload"].get("latency_breakdown") or {}
    if not lb.get("enabled"):
        problems.append("status workload.latency_breakdown missing/disabled")

    # Flight recorder + SLO (ISSUE 15): the ring must hold snapshots, the
    # tracker must have evaluated windows, and workload.slo must carry
    # its honesty flags; the recorder-armed scrape must also pass the
    # extended documented-counter audit.
    from foundationdb_tpu.obs.recorder import FlightRecorder
    from foundationdb_tpu.obs.registry import RECORDER_DOCUMENTED_COUNTERS

    recorder = c.flight_recorder
    ring = FlightRecorder.load(ring_path)
    n_snaps = sum(1 for r in ring if r.get("kind") == "snapshot")
    if n_snaps < 2:
        problems.append(f"flight ring holds {n_snaps} snapshots (< 2)")
    slo = status["workload"].get("slo") or {}
    if not slo.get("enabled"):
        problems.append("status workload.slo missing/disabled")
    for honesty_key in ("warmed_up", "insufficient_p99_windows", "burn"):
        if honesty_key not in slo:
            problems.append(f"workload.slo lacks honesty field "
                            f"{honesty_key!r}")
    reg_rec = c.loop.run(scrape_sim(c), timeout=600)
    reg_rec.add("recorder", "", recorder.metrics())
    reg_rec.add("slo", "", recorder.slo.metrics())
    missing_rec = reg_rec.missing_documented(
        extra=RECORDER_DOCUMENTED_COUNTERS)
    if missing_rec:
        problems.append(
            f"recorder documented counters missing: {missing_rec}")
    recorder.close()
    _os.unlink(ring_path)

    return {
        "metric": "obs_selfcheck",
        "ok": not problems,
        "problems": problems[:20],
        "seed": seed,
        "txns": txns,
        "sample_every": sample_every,
        "txns_sampled": b["txns_sampled"],
        "span_trees_checked": committed_trees,
        "unattributed_frac": b["unattributed_frac"],
        "scrape_metrics": len(reg.values),
        "stages": sorted(b["stages"]),
        "ring_snapshots": n_snaps,
        "slo_windows": slo.get("windows"),
        "slo_warmed_up": slo.get("warmed_up"),
    }


def span_records(seed: int, txns: int = 96, sample_every: int = 4) -> str:
    """Canonical JSON of one seeded run's span records (determinism
    probe: same seed must yield byte-identical output)."""
    c = _new_cluster(seed, obs=True, sample_every=sample_every)
    _drive(c, txns)
    return json.dumps(list(c.loop.span_sink.spans), sort_keys=True)


def run_overhead_ab(seed: int = 11, txns: int = 3072,
                    sample_every: int = 64, reps: int = 3,
                    gate: float = OVERHEAD_GATE,
                    recorder_interval_s: float = 5.0) -> dict:
    """OBS_AB.json: measured throughput overhead on the windowed
    closed-loop sim workload across THREE arms, alternating per rep so
    host drift hits all equally — tracing disabled, 1-in-N sampling, and
    1-in-N sampling + the flight recorder armed (ring to a tmp file at
    its default 5s cadence, the recommended deployment config). Both the
    tracing arm and the recorder arm gate at <=2% vs off."""
    import tempfile

    def arm(obs: bool, recorder: bool = False) -> float:
        ring = None
        if recorder:
            fd, ring = tempfile.mkstemp(prefix="obs_ab_ring_",
                                        suffix=".jsonl")
            os.close(fd)
        c = _new_cluster(seed, obs=obs, sample_every=sample_every,
                         recorder_path=ring,
                         recorder_interval_s=recorder_interval_s)
        t0 = time.perf_counter()
        _drive(c, txns)
        wall = time.perf_counter() - t0
        if ring is not None:
            os.unlink(ring)
        return txns / wall

    tps = {"off": [], "on": [], "rec": []}
    for _ in range(reps):  # alternating arms: drift hits all equally
        tps["off"].append(arm(False))
        tps["on"].append(arm(True))
        tps["rec"].append(arm(True, recorder=True))
    best_off, best_on = max(tps["off"]), max(tps["on"])
    best_rec = max(tps["rec"])
    overhead = 1.0 - best_on / best_off
    rec_overhead = 1.0 - best_rec / best_off
    try:
        load1m = round(os.getloadavg()[0], 2)
    except OSError:
        load1m = None
    return {
        "metric": "obs_sampling_overhead_ab",
        "workload": "closed-loop sim rmw (oracle engine, wall-clocked)",
        "seed": seed,
        "txns_per_rep": txns,
        "reps_per_arm": reps,
        "sample_every": sample_every,
        "recorder_interval_s": recorder_interval_s,
        "txns_per_sec_off": [round(x, 1) for x in tps["off"]],
        "txns_per_sec_on": [round(x, 1) for x in tps["on"]],
        "txns_per_sec_recorder": [round(x, 1) for x in tps["rec"]],
        "best_off_tps": round(best_off, 1),
        "best_on_tps": round(best_on, 1),
        "best_recorder_tps": round(best_rec, 1),
        "overhead_frac": round(overhead, 4),
        "recorder_overhead_frac": round(rec_overhead, 4),
        "gate_frac": gate,
        # Honesty flags (repo convention): CPU-only sim, no TPU run
        # attempted or claimed; wall-clock measurement, so the host's
        # load rides along for the reader.
        "valid": overhead <= gate and rec_overhead <= gate,
        "cpu_fallback": False,
        "host": {"loadavg_1m": load1m,
                 "cores": (len(os.sched_getaffinity(0))
                           if hasattr(os, "sched_getaffinity")
                           else os.cpu_count())},
    }


async def latency_probe(db, loop, n: int = 48,
                        key_prefix: bytes = b"obs/probe/") -> dict:
    """Active commit-path latency probe (cli `latency`): run `n` small
    txns with every one sampled, return the per-stage breakdown. Uses a
    dedicated always-sample sink swapped in for the probe and restored
    after, so a cluster's own 1-in-N sink keeps its population."""
    from foundationdb_tpu.obs.span import SpanSink

    prev = getattr(loop, "span_sink", None)
    sink = SpanSink(loop, sample_every=1)
    try:
        for k in range(n):
            key = key_prefix + b"%d" % (k % 16)

            async def body(tr, key=key):
                v = await tr.get(key)
                tr.set(key, b"%d" % (int(v or b"0") + 1))

            await db.run(body)
        report = sink.breakdown()
        if "resolve_wait" not in report["stages"]:
            # Commits were answered without proxy spans: the server side
            # is running untraced, so everything past the GRV landed in
            # `unattributed`. Say so — an empty stage table with no
            # explanation is how attribution tools lose trust.
            report["warning"] = (
                "server-side tracing is not armed (start server processes "
                "with FDB_TPU_OBS=1): only client-side stages attributed, "
                "the commit round trip is reported as unattributed")
        return report
    finally:
        if prev is not None:
            loop.span_sink = prev
        else:
            del loop.span_sink
