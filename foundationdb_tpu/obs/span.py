"""Commit-lifecycle tracing: per-transaction spans + stage histograms.

Every perf record so far measured the commit path at its edges — p99
moved, but WHERE a transaction spent its time was invisible (the kernel
profiler's own ``unattributed_ms`` admits the gap). This module is the
runtime-side answer: a sampled transaction carries a trace context
(txn trace id) through the wire structs, every role stamps span
boundaries, and the CLIENT assembles the exact per-transaction breakdown
from the proxy's piggybacked stage spans (CommitResult.spans), so the
identity

    e2e == sum(stage durations) + unattributed

holds by ARITHMETIC per sampled transaction — the residue is reported,
never silently dropped. The reference's TraceEvent backbone stops at
per-role events; this is the FAFO-style exact per-stage cost attribution
(arxiv 2507.10757) the multi-core open-loop re-run needs to be
diagnosable.

Design rules:

- **Off by default, cheap when on.** No sink attached → role code takes
  one ``getattr`` and moves on. With a sink, only 1-in-N transactions
  (``sample_every``, default 64) pay the per-txn work; per-batch stamps
  (coalescer queue, tlog fsync) are amortized over the whole batch.
- **Deterministic in sim.** Sampling is counter-based (never RNG — it
  must not perturb the loop's seeded stream), trace ids are sequential,
  and all stamps come off the loop's virtual clock, so the same seed
  yields byte-identical span records. On a RealLoop, trace ids carry the
  pid so records from parallel generator processes never collide, and
  synchronous engine work is measured with ``time.perf_counter`` (the
  virtual clock cannot advance inside one task step there).
- **One histogram machinery.** Per-stage distributions reuse loadgen's
  mergeable log-binned ``LatencyHistogram`` — scrape lines from many
  processes SUM into one honest population percentile.

Stage vocabulary (``TXN_STAGES`` is an exclusive partition of a sampled
transaction's commit-path time; ``SUB_STAGES`` attribute the interior of
``resolve_wait``/``grv_wait`` at batch granularity and never enter the
reconciliation identity):

    grv_wait      client: read-version request -> grant (includes the GRV
                  proxy queue and any admission-saturation deferral)
    proxy_admit   proxy: commit arrival -> popped by batch formation
                  (lane queue; includes the admission probe)
    shaped_park   proxy: time parked in the admission shaped lane (0
                  unless shaped)
    batch_form    proxy: popped -> commit version acquired
    resolve_wait  proxy: version -> resolver verdicts (network + the
                  resolver sub-stages below)
    wave_apply    proxy: verdicts -> mutations assembled in (wave, index)
                  order
    tlog_durable  proxy: assemble -> every tlog acked the push fsync'd
    commit_publish proxy: durable -> reply send (sequencer committed-
                  version report, admission filter feed)
    reply         client: commit RPC round trip minus the proxy's total
                  (request + reply transport legs)

    grv_proxy_queue   GRV proxy: request arrival -> batch admit
    coalesce_queue    resolver: chain admission -> dispatch group start
    host_pack         resolver: engine host-side pack (engines that
                      publish ``last_host_pack_s``)
    device_dispatch   resolver: modeled dispatch cost + engine execution
                      (under the global wave protocol: both phases'
                      engine work, edges + level/paint)
    wave_exchange     resolver: global wave commit only — phase-1 reply
                      to phase-2 arrival (the proxy's OR-reduce of the
                      shards' edge bitsets plus both network legs), the
                      comms cost the sharded schedule pays per window
    wave_level        resolver: global wave commit only — the phase-2
                      leveling + paint (interior of device_dispatch)
    spec_resolve      resolver: speculative dispatch only
                      (FDB_TPU_SPEC_RESOLVE) — window N+1's resolve
                      dispatched against N's optimistic paint (interior
                      of device_dispatch, the phase-A half)
    reconcile         resolver: speculative dispatch only — collect +
                      reconcile through the engine ring, including any
                      rollback/repair re-resolves (interior of
                      device_dispatch, the phase-B half)
    tlog_fsync        tlog: chain-ordered push -> durable ack
"""

from __future__ import annotations

import os
import time
from collections import deque

from foundationdb_tpu.loadgen.harness import LatencyHistogram

#: Exclusive partition of a sampled txn's commit-path time: the
#: reconciliation identity is  e2e == sum(TXN_STAGES) + unattributed.
TXN_STAGES = (
    "grv_wait",
    "proxy_admit",
    "shaped_park",
    "batch_form",
    "resolve_wait",
    "wave_apply",
    "tlog_durable",
    "commit_publish",
    "reply",
)

#: Batch/role-level attribution INSIDE the txn stages (never summed into
#: the identity — they live within grv_wait / resolve_wait / tlog_durable).
SUB_STAGES = (
    "grv_proxy_queue",
    "coalesce_queue",
    "host_pack",
    "device_dispatch",
    "wave_exchange",
    "wave_level",
    "spec_resolve",
    "reconcile",
    "tlog_fsync",
)

#: Read-plane batch-level stages (foundationdb_tpu/reads/): stamped via
#: stage_tick by the storage-side coalescer and the per-version watch
#: sweep. Like SUB_STAGES they never sum into the TXN identity (reads are
#: not commits), but they ride the same histograms/span export, so `cli
#: latency`, the flight recorder, and the doctor's attribution see the
#: read plane next to the commit path.
READ_STAGES = (
    "read_coalesce",
    "read_pack",
    "read_dispatch",
    "watch_sweep",
)


def obs_env_default() -> bool:
    """FDB_TPU_OBS env default (validated via the kernel flags' shared
    env_choice: unknown values raise with the accepted list)."""
    from foundationdb_tpu.core.types import env_choice

    return env_choice("FDB_TPU_OBS", "0", ("0", "1")) == "1"


def obs_sample_default() -> int:
    """FDB_TPU_OBS_SAMPLE: sample 1-in-N transactions (default 64)."""
    raw = os.environ.get("FDB_TPU_OBS_SAMPLE", "64")
    try:
        n = int(raw)
        if n < 1:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"FDB_TPU_OBS_SAMPLE={raw!r} invalid: want an integer >= 1"
        ) from None
    return n


class TraceContext:
    """A sampled transaction's trace identity, propagated through the
    wire structs (CommitRequest.trace). Existence == sampled: unsampled
    transactions carry None and cost nothing downstream."""

    __slots__ = ("tid",)

    def __init__(self, tid: int):
        self.tid = tid

    def __repr__(self) -> str:
        return f"TraceContext({self.tid:#x})"


class SpanSink:
    """Per-loop span collector: ring of span records + per-stage mergeable
    histograms. Attaches as ``loop.span_sink`` (the Tracer convention) so
    role code reaches it ambiently.

    Span records are plain dicts ``{tid, name, start, dur, process}``
    (``version`` for batch-level records); ``start``/``dur`` are seconds
    on the emitting process's loop clock, rounded to 9 decimals so sim
    records are byte-identical under a seed."""

    def __init__(self, loop, sample_every: int | None = None,
                 ring_size: int = 8192, enabled: bool = True):
        self.loop = loop
        self.sample_every = (obs_sample_default() if sample_every is None
                             else max(1, int(sample_every)))
        self.enabled = enabled
        self.spans: deque[dict] = deque(maxlen=ring_size)
        self.stage_hists: dict[str, LatencyHistogram] = {}
        self.e2e_hist = LatencyHistogram()
        self.unattributed_hist = LatencyHistogram()
        self._sample_counter = 0
        self._stage_ticks: dict[str, int] = {}
        self._spans_dropped = 0  # ring evictions (maxlen overflow)
        self._next_tid = 0
        # RealLoop (deployed / loadgen generator): pid-salted trace ids so
        # parallel processes never collide. Never in sim — determinism.
        self._tid_base = (
            (os.getpid() & 0xFFFF) << 40
            if getattr(loop, "WALL_TIME", False) else 0
        )
        self.txns_sampled = 0
        self.txns_seen = 0
        loop.span_sink = self

    # -- sampling ------------------------------------------------------------

    def sample(self) -> TraceContext | None:
        """1-in-N counter-based sampling decision (deterministic: never
        draws from the loop RNG). Returns a TraceContext or None."""
        if not self.enabled:
            return None
        self.txns_seen += 1
        self._sample_counter += 1
        if self._sample_counter < self.sample_every:
            return None
        self._sample_counter = 0
        self._next_tid += 1
        self.txns_sampled += 1
        return TraceContext(self._tid_base | self._next_tid)

    # -- recording -----------------------------------------------------------

    def _hist(self, name: str) -> LatencyHistogram:
        h = self.stage_hists.get(name)
        if h is None:
            h = self.stage_hists[name] = LatencyHistogram()
        return h

    def record_stage(self, name: str, dur_s: float, n: int = 1) -> None:
        """Histogram-only stage sample (batch-level sub-stages)."""
        self._hist(name).record_n(dur_s * 1e3, n)

    def stage_tick(self, name: str, dur_s: float, n: int = 1,
                   version: "int | None" = None) -> None:
        """Sampled sub-stage record: 1-in-sample_every per stage NAME,
        counter-based (deterministic). The population sub-stages
        (grv_proxy_queue, tlog_fsync, per-batch resolver stages) ride the
        commit hot path on EVERY request while tracing is armed — at full
        recording they alone cost ~10% throughput, which would fail the
        subsystem's own overhead gate. They are distribution detail, not
        part of the reconciliation identity, so sampling them like the
        txn spans keeps the gate honest and the histograms statistical.

        ``version``: also ring a batch-level span record for the sampled
        tick (tid None, the batch's commit version attached) so the
        Chrome-trace/Perfetto export shows the sub-stage on the emitting
        role's track — the mesh wave stages (wave_exchange/wave_level)
        pass it so the sharded protocol's comms/level cost is visible on
        the timeline, not only in the flat tallies."""
        c = self._stage_ticks.get(name, 0) + 1
        if c >= self.sample_every:
            self._stage_ticks[name] = 0
            self.record_stage(name, dur_s, n)
            if version is not None:
                self.add_span(None, name, self.loop.now - dur_s, dur_s,
                              version=version)
        else:
            self._stage_ticks[name] = c

    def add_span(self, tid: "int | None", name: str, start: float,
                 dur: float, process: str | None = None,
                 version: "int | None" = None) -> None:
        """One span record for the tree/timeline (ring-buffered)."""
        if process is None:
            cur = getattr(self.loop, "_current", None)
            process = cur.process if cur is not None else "<main>"
        rec = {
            "tid": tid,
            "name": name,
            "start": round(start, 9),
            "dur": round(dur, 9),
            "process": process,
        }
        if version is not None:
            rec["version"] = version
        if len(self.spans) == self.spans.maxlen:
            self._spans_dropped += 1  # eviction truncates the OLDEST tid
        self.spans.append(rec)

    def record_txn(self, tid: int, e2e_s: float,
                   stages: "list[tuple[str, float, float]]") -> float:
        """One sampled transaction's assembled breakdown: ``stages`` is
        [(stage name, absolute start, duration), ...] in TXN_STAGES
        vocabulary. Records the span tree, the per-stage histograms, the
        end-to-end histogram, and the arithmetic residue; returns the
        residue (seconds). Negative residue is clamped to 0 for the
        histogram but preserved in the span record — a negative value
        would mean double-counted stages and must stay visible."""
        attributed = 0.0
        for name, start, dur in stages:
            self.add_span(tid, name, start, dur)
            self._hist(name).record(dur * 1e3)
            attributed += dur
        unattributed = e2e_s - attributed
        start0 = min((start for _n, start, _d in stages), default=0.0)
        self.add_span(tid, "e2e", start0, e2e_s)
        self.add_span(tid, "unattributed", 0.0, round(unattributed, 9))
        self.e2e_hist.record(e2e_s * 1e3)
        self.unattributed_hist.record(max(0.0, unattributed) * 1e3)
        return unattributed

    # -- query ---------------------------------------------------------------

    def spans_for(self, tid: int) -> list[dict]:
        return [s for s in self.spans if s["tid"] == tid]

    def sampled_tids(self, complete_only: bool = False) -> list[int]:
        """Distinct tids in the ring, oldest first. ``complete_only``
        drops the OLDEST tid whenever the ring has evicted records: a
        txn's spans are appended as one contiguous block (record_txn),
        so front-eviction can truncate only the oldest surviving tid —
        completeness gates must not read that truncation as a missing
        stage (a false alarm that would only fire at scale)."""
        seen: dict[int, None] = {}
        for s in self.spans:
            if s["tid"] is not None:
                seen.setdefault(s["tid"])
        tids = list(seen)
        if complete_only and self._spans_dropped and tids:
            tids = tids[1:]
        return tids

    def breakdown(self) -> dict:
        """The latency_breakdown document (status JSON / cli latency):
        per-stage count/mean/p50/p99 plus the reconciliation block. The
        identity is judged on SUMS (exact arithmetic), not percentiles:
        attributed_ms + unattributed_ms == e2e_ms up to float rounding,
        with unattributed_frac the honesty headline."""
        stages = {
            name: {
                "count": h.count,
                "mean_ms": round(h.mean(), 4),
                "p50_ms": h.percentile(50),
                "p99_ms": h.percentile(99),
                "sum_ms": round(h.sum_ms, 4),
            }
            for name, h in sorted(self.stage_hists.items())
        }
        attributed_ms = sum(
            h.sum_ms for name, h in self.stage_hists.items()
            if name in TXN_STAGES
        )
        e2e_ms = self.e2e_hist.sum_ms
        unattributed_ms = e2e_ms - attributed_ms
        return {
            "enabled": self.enabled,
            "sample_every": self.sample_every,
            "txns_seen": self.txns_seen,
            "txns_sampled": self.txns_sampled,
            "stages": stages,
            "e2e": {
                "count": self.e2e_hist.count,
                "mean_ms": round(self.e2e_hist.mean(), 4),
                "p50_ms": self.e2e_hist.percentile(50),
                "p99_ms": self.e2e_hist.percentile(99),
                "sum_ms": round(e2e_ms, 4),
            },
            "attributed_ms": round(attributed_ms, 4),
            "unattributed_ms": round(unattributed_ms, 4),
            "unattributed_frac": (
                round(max(0.0, unattributed_ms) / e2e_ms, 4)
                if e2e_ms > 0 else 0.0
            ),
        }

    def dump(self) -> dict:
        """Mergeable raw form (histograms as bin lists): what crosses
        process boundaries — loadgen generators emit this next to their
        open-loop accounting and bench merges by histogram sum."""
        return {
            "sample_every": self.sample_every,
            "txns_seen": self.txns_seen,
            "txns_sampled": self.txns_sampled,
            "stages": {n: h.to_dict()
                       for n, h in sorted(self.stage_hists.items())},
            "e2e": self.e2e_hist.to_dict(),
            "unattributed": self.unattributed_hist.to_dict(),
        }

    @classmethod
    def merge_dumps(cls, dumps: "list[dict]") -> dict:
        """Sum several dump() documents (cross-process aggregation) and
        return a breakdown-shaped report over the merged population."""
        dumps = [d for d in dumps if d]
        stage_hists: dict[str, LatencyHistogram] = {}
        e2e = LatencyHistogram()
        seen = sampled = 0
        sample_every = 0
        for d in dumps:
            seen += d.get("txns_seen", 0)
            sampled += d.get("txns_sampled", 0)
            sample_every = max(sample_every, d.get("sample_every", 0))
            e2e.merge(LatencyHistogram.from_dict(d.get("e2e", {})))
            for name, hd in (d.get("stages") or {}).items():
                h = stage_hists.setdefault(name, LatencyHistogram())
                h.merge(LatencyHistogram.from_dict(hd))
        attributed_ms = sum(
            h.sum_ms for n, h in stage_hists.items() if n in TXN_STAGES
        )
        e2e_ms = e2e.sum_ms
        return {
            "merged_from": len(dumps),
            "sample_every": sample_every,
            "txns_seen": seen,
            "txns_sampled": sampled,
            "stages": {
                n: {"count": h.count, "mean_ms": round(h.mean(), 4),
                    "p50_ms": h.percentile(50), "p99_ms": h.percentile(99),
                    "sum_ms": round(h.sum_ms, 4)}
                for n, h in sorted(stage_hists.items())
            },
            "e2e": {"count": e2e.count, "mean_ms": round(e2e.mean(), 4),
                    "p50_ms": e2e.percentile(50),
                    "p99_ms": e2e.percentile(99),
                    "sum_ms": round(e2e_ms, 4)},
            "attributed_ms": round(attributed_ms, 4),
            "unattributed_ms": round(e2e_ms - attributed_ms, 4),
            "unattributed_frac": (
                round(max(0.0, e2e_ms - attributed_ms) / e2e_ms, 4)
                if e2e_ms > 0 else 0.0
            ),
        }

    def to_chrome_trace(self) -> dict:
        """Chrome-trace/Perfetto timeline of the sampled window: complete
        ("X") events, one track per emitting process, span name + trace
        id in args. Load via chrome://tracing or ui.perfetto.dev."""
        events = []
        pids: dict[str, int] = {}
        for s in self.spans:
            pid = pids.setdefault(s["process"], len(pids) + 1)
            events.append({
                "name": s["name"],
                "ph": "X",
                "pid": pid,
                "tid": (s["tid"] or 0) & 0xFFFFFFFF,
                "ts": round(s["start"] * 1e6, 3),
                "dur": round(s["dur"] * 1e6, 3),
                "args": {k: v for k, v in s.items()
                         if k in ("tid", "version", "process")},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "source": "foundationdb_tpu.obs",
                "processes": {str(v): k for k, v in pids.items()},
            },
        }

    def reset(self) -> None:
        """Clear collected spans/histograms (ladder points reuse one
        sink); the sampling counter and tid sequence keep running."""
        self.spans.clear()
        self._spans_dropped = 0
        self.stage_hists = {}
        self.e2e_hist = LatencyHistogram()
        self.unattributed_hist = LatencyHistogram()
        self.txns_sampled = 0
        self.txns_seen = 0


#: A committed sampled txn's tree must contain ALL of these (shaped_park
#: only when the txn rode the shaped lane).
REQUIRED_TREE = frozenset(
    s for s in TXN_STAGES if s != "shaped_park"
) | {"e2e", "unattributed"}

#: The proxy-side stages that must PARTITION [arrival, reply send]
#: contiguously — a gap here is a stage the proxy forgot to stamp.
_PROXY_CHAIN = ("proxy_admit", "shaped_park", "batch_form", "resolve_wait",
                "wave_apply", "tlog_durable", "commit_publish")


def check_txn_tree(spans: "list[dict]", tol: float = 1e-6) -> list[str]:
    """Completeness check for ONE sampled transaction's span records:
    every commit-path stage present, and the proxy chain contiguous (no
    stage gaps). Returns problems; empty == complete."""
    names = {s["name"] for s in spans}
    problems = [f"missing stage: {n}" for n in sorted(REQUIRED_TREE - names)]
    chain = sorted((s for s in spans if s["name"] in _PROXY_CHAIN),
                   key=lambda s: s["start"])
    for prev, nxt in zip(chain, chain[1:]):
        gap = nxt["start"] - (prev["start"] + prev["dur"])
        if abs(gap) > tol:
            problems.append(
                f"gap {gap:.9f}s between {prev['name']} and {nxt['name']}")
    # Per-txn reconciliation identity, straight off the records.
    e2e = sum(s["dur"] for s in spans if s["name"] == "e2e")
    attributed = sum(s["dur"] for s in spans if s["name"] in TXN_STAGES)
    resid = sum(s["dur"] for s in spans if s["name"] == "unattributed")
    if abs(e2e - attributed - resid) > tol:
        problems.append(
            f"identity broken: e2e {e2e:.9f} != attributed {attributed:.9f}"
            f" + unattributed {resid:.9f}")
    return problems


def span_sink(loop) -> "SpanSink | None":
    """The loop's span sink when tracing is armed and enabled, else None.
    THE hot-path gate: every role call site is
    ``sink = span_sink(loop)`` + ``if sink is not None`` — one getattr
    when tracing is off."""
    s = getattr(loop, "span_sink", None)
    return s if s is not None and s.enabled else None


def stage_clock(loop):
    """Clock for SYNCHRONOUS work (engine resolve, host pack): the loop
    clock cannot advance inside one task step on a RealLoop, so deployed
    processes measure with perf_counter; sim keeps the virtual clock so
    records stay seed-deterministic (synchronous work is 0 virtual
    seconds there, honestly reported as such)."""
    if getattr(loop, "WALL_TIME", False):
        return time.perf_counter
    return lambda: loop.now
