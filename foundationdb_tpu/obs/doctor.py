"""Incident doctor: deterministic root-cause reports from a flight ring.

``diagnose()`` ingests a flight-recorder ring (obs/recorder.py JSONL)
and answers "why did the SLO burn" without any hand-joining:

1. re-runs the SloTracker over the ring's snapshots (pure function of
   the ring — same ring, same report, byte for byte) to find anomaly
   incident windows;
2. for each incident, attributes the **dominant stage**: the commit-path
   stage whose share of end-to-end latency GREW most inside the window,
   computed from the snapshots' cumulative per-stage sums
   (obs.stage_sum_ms.*, diffed at the window edges against the
   pre-window baseline);
3. collects the **co-occurring annotations** (recovery stages, chaos
   fault/heal stamps, ratekeeper limiting transitions, resolver-queue
   crossings, admission engage/release, reshards, scrape gaps) inside
   the slack-padded window;
4. emits one machine-readable verdict per incident plus a one-line
   human summary ("goodput 3.1 vs baseline 77.2 tps in [11.0,16.0]s:
   dominant stage resolve_wait (12%→64%); co-occurring: recovery
   RecoveryCompleted@12.4 (salvage 1.4s), chaos_fault kill tlog0@11.2").

``attribute_faults()`` is the chaos cross-check: every injected fault
window (chaos_fault → matching chaos_heal annotation, grace-padded)
must contain an annotation of its EXPECTED class — a kill/partition/
pause that the cluster survived shows up as a recovery. ``run_doctor_
gate()`` runs the seeded mini-chaos script with the recorder armed and
gates exactly that, as one JSON line (tpuwatch ``doctor`` stage).

Surfaces: ``cli doctor RING.jsonl``, ``python -m foundationdb_tpu.obs
--doctor RING.jsonl`` and ``--doctor-gate``.
"""

from __future__ import annotations

import json

from foundationdb_tpu.obs.recorder import FlightRecorder
from foundationdb_tpu.obs.slo import SloTracker

#: chaos action -> annotation class its window MUST contain (the chaos
#: battery already gates that kills produce recoveries; the doctor's
#: job is attributing them to the right window on the timeline).
EXPECTED_FAULT_CLASS = {
    "kill": "recovery",
    "partition": "recovery",
    "pause": "recovery",
}

#: padding around windows when matching annotations: detection latency
#: plus scrape cadence mean an effect can land a few seconds after its
#: cause was stamped.
SLACK_S = 5.0


def split_ring(records: list[dict]) -> tuple[list, list, list]:
    """(snapshots, annotations, gaps) in ring order."""
    snaps = [r for r in records if r.get("kind") == "snapshot"]
    anns = [r for r in records if r.get("kind") == "annotation"]
    gaps = [r for r in records if r.get("kind") == "gap"]
    return snaps, anns, gaps


# -- dominant-stage attribution ------------------------------------------------


def _stage_sums(snap: dict) -> tuple[dict[str, float], float]:
    """({stage: cumulative sum_ms}, cumulative e2e sum_ms) of one
    snapshot's aggregated metrics. TXN_STAGES only: those partition the
    e2e time (the reconciliation identity), so their sums are shares of
    the same denominator — SUB_STAGES (device_dispatch, tlog_fsync,
    wave_*) nest INSIDE them and tick on their own batch-weighted
    sampling, so counting them here can "win" with a share far above
    100% and name a sub-stage as the dominant commit-path stage."""
    from foundationdb_tpu.obs.span import TXN_STAGES

    pref = "obs.stage_sum_ms."
    m = snap.get("metrics") or {}
    return ({k[len(pref):]: float(v) for k, v in m.items()
             if k.startswith(pref) and k[len(pref):] in TXN_STAGES},
            float(m.get("obs.e2e_sum_ms", 0.0)))


def _read_stage_sums(snap: dict) -> tuple[dict[str, float], float]:
    """({stage: cumulative sum_ms}, total read-plane sum_ms) of one
    snapshot. READ_STAGES live OUTSIDE the txn reconciliation identity
    (reads never enter the commit pipeline), so they get their own
    denominator: the total time the read plane itself burned. That keeps
    a read storm from being hidden by (or polluting) the commit-path
    shares above."""
    from foundationdb_tpu.obs.span import READ_STAGES

    pref = "obs.stage_sum_ms."
    m = snap.get("metrics") or {}
    sums = {k[len(pref):]: float(v) for k, v in m.items()
            if k.startswith(pref) and k[len(pref):] in READ_STAGES}
    return sums, sum(sums.values())


def _snap_at(snaps: list[dict], t: float, after: bool) -> "dict | None":
    """Last snapshot at/before t (after=False) or first at/after t."""
    if after:
        for s in snaps:
            if s["t"] >= t:
                return s
        return snaps[-1] if snaps else None
    prev = None
    for s in snaps:
        if s["t"] > t:
            break
        prev = s
    return prev if prev is not None else (snaps[0] if snaps else None)


def dominant_stage(snaps: list[dict], t0: float, t1: float) -> "dict | None":
    """The stage whose share of e2e GREW most inside [t0, t1] vs the
    pre-window baseline. None (an honesty signal, not a silent zero)
    when the window or baseline saw no attributed latency at all —
    e.g. tracing was not armed, or no sampled txn completed."""
    if not snaps:
        return None
    first = snaps[0]
    a = _snap_at(snaps, t0, after=False)
    b = _snap_at(snaps, t1, after=True)
    if a is None or b is None or b["t"] <= a["t"]:
        return None
    sums_a, e2e_a = _stage_sums(a)
    sums_b, e2e_b = _stage_sums(b)
    sums_f, e2e_f = _stage_sums(first)
    d_e2e = e2e_b - e2e_a
    base_e2e = e2e_a - e2e_f
    if d_e2e <= 0:
        return None

    def shares(sums_hi, sums_lo, denom):
        if denom <= 0:
            return {}
        return {s: max(0.0, sums_hi.get(s, 0.0) - sums_lo.get(s, 0.0))
                / denom for s in set(sums_hi) | set(sums_lo)}

    during = shares(sums_b, sums_a, d_e2e)
    before = shares(sums_a, sums_f, base_e2e)
    if not during:
        return None
    best = max(during, key=lambda s: during[s] - before.get(s, 0.0))
    return {
        "stage": best,
        "share_during": round(during[best], 4),
        "share_before": round(before.get(best, 0.0), 4),
        "share_growth": round(during[best] - before.get(best, 0.0), 4),
        "window_e2e_ms": round(d_e2e, 3),
        "baseline_windows": bool(base_e2e > 0),
    }


def dominant_read_stage(snaps: list[dict], t0: float, t1: float) -> "dict | None":
    """Read-plane twin of dominant_stage: the READ_STAGES member whose
    share of the read plane's own time GREW most inside [t0, t1]. None
    when the window saw no read-plane latency — either the read path
    ran unbatched (stages never tick) or nothing was read. A read storm
    shows up here (read_dispatch / watch_sweep dominating) even when the
    commit-path attribution above is quiet."""
    if not snaps:
        return None
    first = snaps[0]
    a = _snap_at(snaps, t0, after=False)
    b = _snap_at(snaps, t1, after=True)
    if a is None or b is None or b["t"] <= a["t"]:
        return None
    sums_a, tot_a = _read_stage_sums(a)
    sums_b, tot_b = _read_stage_sums(b)
    sums_f, tot_f = _read_stage_sums(first)
    d_tot = tot_b - tot_a
    base_tot = tot_a - tot_f
    if d_tot <= 0:
        return None

    def shares(sums_hi, sums_lo, denom):
        if denom <= 0:
            return {}
        return {s: max(0.0, sums_hi.get(s, 0.0) - sums_lo.get(s, 0.0))
                / denom for s in set(sums_hi) | set(sums_lo)}

    during = shares(sums_b, sums_a, d_tot)
    before = shares(sums_a, sums_f, base_tot)
    if not during:
        return None
    best = max(during, key=lambda s: during[s] - before.get(s, 0.0))
    return {
        "stage": best,
        "share_during": round(during[best], 4),
        "share_before": round(before.get(best, 0.0), 4),
        "share_growth": round(during[best] - before.get(best, 0.0), 4),
        "window_read_ms": round(d_tot, 3),
        "baseline_windows": bool(base_tot > 0),
    }


def misspec_storm(snaps: list[dict], t0: float, t1: float,
                  threshold: float = 0.5) -> "dict | None":
    """Mis-speculation storm detector (FDB_TPU_SPEC_RESOLVE): what share
    of the windows speculated inside [t0, t1] rolled back through the
    repair path, from the resolvers' cumulative ``spec_dispatched`` /
    ``spec_repaired`` counters in the ring snapshots. Returns None when
    nothing speculated in the window (serial engine, or the ratekeeper's
    depth clamp already shut speculation off) — an honesty signal, like
    dominant_stage's. ``storm`` trips at ``threshold``, matching the
    coalescer's MISSPEC_CLAMP: past it every other window re-resolves,
    so speculation is adding snapshot+repair work, not hiding latency."""
    if not snaps:
        return None
    a = _snap_at(snaps, t0, after=False)
    b = _snap_at(snaps, t1, after=True)
    if a is None or b is None or b["t"] <= a["t"]:
        return None

    def sums(snap: dict, leaf: str) -> float:
        m = snap.get("metrics") or {}
        return sum(float(v) for k, v in m.items()
                   if k.startswith("resolver.") and k.endswith("." + leaf))

    disp = sums(b, "spec_dispatched") - sums(a, "spec_dispatched")
    rep = sums(b, "spec_repaired") - sums(a, "spec_repaired")
    if disp <= 0:
        return None
    rate = max(0.0, rep) / disp
    return {
        "spec_dispatched": int(disp),
        "spec_repaired": int(rep),
        "misspec_rate": round(rate, 4),
        "storm": bool(rate >= threshold),
    }


def dict_thrash(snaps: list[dict], t0: float, t1: float,
                threshold: float = 0.5,
                min_events: int = 64) -> "dict | None":
    """Tiered-dictionary thrash detector (FDB_TPU_DICT_HOT_CAPACITY):
    inside [t0, t1], did promotions keep pace with demotions? A hot set
    that FITS the HBM tier demotes cold keys that stay cold (promotion
    rate ~ 0); promotion rate ≈ demotion rate means the engine keeps
    re-promoting what it just demoted — the hot working set exceeds the
    hot tier, and every round trip ships delta rows for keys that should
    have stayed resident. From the resolvers' cumulative
    ``engine.demotions`` / ``engine.promotions`` counters in the ring
    snapshots. Returns None when nothing demoted in the window (tiering
    off, or the tier is simply big enough) — the honesty signal, like
    misspec_storm's. ``thrash`` trips when both flows are material
    (>= min_events demotions) and the smaller flow is at least
    ``threshold`` of the larger."""
    if not snaps:
        return None
    a = _snap_at(snaps, t0, after=False)
    b = _snap_at(snaps, t1, after=True)
    if a is None or b is None or b["t"] <= a["t"]:
        return None

    def sums(snap: dict, leaf: str) -> float:
        m = snap.get("metrics") or {}
        return sum(float(v) for k, v in m.items()
                   if k.startswith("resolver.") and k.endswith("." + leaf))

    dem = sums(b, "demotions") - sums(a, "demotions")
    pro = sums(b, "promotions") - sums(a, "promotions")
    if dem <= 0:
        return None
    rate = max(0.0, pro) / dem
    return {
        "demotions": int(dem),
        "promotions": int(pro),
        "promotion_rate": round(rate, 4),
        "thrash": bool(dem >= min_events and min(dem, max(pro, 0.0))
                       >= threshold * max(dem, pro)),
    }


def scale_relief(records: list[dict], slack_s: float = SLACK_S,
                 grace_s: float = 60.0) -> "list | None":
    """Autoscale attribution (autoscale/): per scale event on the ring
    (`AutoscaleRecruit`/`AutoscaleRetire` annotations, cls="autoscale"),
    did the TRIGGERING signal clear after the fleet changed? The
    annotation carries the aggregated-scrape key it fired on (`metric`)
    and the policy's clear threshold (`clear_below`); relief is the
    first ring snapshot after the event where that key reads below the
    threshold (`above=True` events clear upward — a goodput floor).
    Returns None when the ring holds NO autoscale annotations — the
    autoscaler was unarmed, and claiming "no scale events needed relief"
    would be vacuously true (the honesty signal, like dominant_stage's).
    Scale-downs triggered by slack (no `clear_below`) attribute on the
    signal alone: there is no limiting signal left to clear."""
    snaps, anns, _gaps = split_ring(records)
    armed = [a for a in anns if a.get("cls") == "autoscale"]
    if not armed:
        return None
    # Relief confirmations ("AutoscaleRelief") prove the loop was armed
    # but are not scale events themselves — attributing them would be
    # vacuous double-counting.
    events = [a for a in armed
              if a.get("name") in ("AutoscaleRecruit", "AutoscaleRetire")]
    out = []
    for e in events:
        t0 = e["t"]
        metric, clear = e.get("metric"), e.get("clear_below")
        above = bool(e.get("clear_above", False))
        relieved_at = None
        if metric is not None and clear is not None:
            for s in snaps:
                if s["t"] <= t0 or s["t"] > t0 + grace_s:
                    continue
                v = (s.get("metrics") or {}).get(metric)
                if v is None:
                    continue
                if (float(v) > float(clear)) if above \
                        else (float(v) < float(clear)):
                    relieved_at = s["t"]
                    break
        needs_clear = metric is not None and clear is not None
        out.append({
            "name": e.get("name"),
            "role": e.get("role"),
            "signal": e.get("signal"),
            "from_n": e.get("from_n"),
            "to_n": e.get("to_n"),
            "t": t0,
            "metric": metric,
            "clear_below": clear,
            "relieved": (relieved_at is not None) if needs_clear else None,
            "relief_s": (round(relieved_at - t0, 3)
                         if relieved_at is not None else None),
            "attributed": bool(e.get("signal")) and (
                relieved_at is not None if needs_clear else True),
        })
    return out


# -- annotations in a window ---------------------------------------------------


def annotations_in(anns: list[dict], t0: float, t1: float,
                   slack_s: float = SLACK_S,
                   exclude_cls: tuple = ()) -> list[dict]:
    out = [a for a in anns
           if t0 - slack_s <= a["t"] <= t1 + slack_s
           and a.get("cls") not in exclude_cls]
    return sorted(out, key=lambda a: a["t"])


def _ann_brief(a: dict) -> str:
    extra = ""
    if a.get("name") == "RecoveryCompleted" and a.get("salvage_s") is not None:
        extra = f" (salvage {a['salvage_s']}s)"
    elif a.get("cls") == "chaos_fault":
        extra = f" {a.get('action', '')} {a.get('target', '')}".rstrip()
    elif a.get("name") == "RkLimitReasonChanged":
        extra = f" -> {a.get('reason')}"
    elif a.get("cls") == "resolver_queue":
        extra = f" depth_hw={a.get('depth_hw')}"
    return f"{a.get('cls')}:{a.get('name')}@{a['t']:.1f}{extra}"


# -- the report ----------------------------------------------------------------


def diagnose(records: list[dict], objectives: "dict | None" = None,
             slack_s: float = SLACK_S) -> dict:
    """Deterministic doctor report over one ring (see module docstring)."""
    snaps, anns, gaps = split_ring(records)
    tracker = SloTracker(objectives)
    for s in snaps:
        tracker.observe(s["t"], s.get("metrics") or {})
    incidents = []
    for inc in tracker.incidents:
        t0, t1 = inc["t0"], inc["t1"]
        co = annotations_in(anns, t0, t1, slack_s)
        co_gaps = [g for g in gaps if t0 - slack_s <= g["t"] <= t1 + slack_s]
        stage = dominant_stage(snaps, t0, t1)
        read_stage = dominant_read_stage(snaps, t0, t1)
        misspec = misspec_storm(snaps, t0, t1)
        thrash = dict_thrash(snaps, t0, t1)
        verdict = {
            "window": [t0, t1],
            "sli": inc["sli"],
            "observed": inc["observed"],
            "baseline_mean": inc["baseline_mean"],
            "windows": inc["windows"],
            "dominant_stage": stage,
            "dominant_read_stage": read_stage,
            "misspec": misspec,
            "dict_thrash": thrash,
            "annotations": co,
            "annotation_classes": sorted(
                {a.get("cls") for a in co}
                | ({"scrape_gap"} if co_gaps else set())),
            "scrape_gaps": len(co_gaps),
        }
        stage_txt = (
            f"dominant stage {stage['stage']} "
            f"({stage['share_before']:.0%}->{stage['share_during']:.0%})"
            if stage else "no stage attribution (tracing not armed or no "
                          "sampled txns in window)")
        if read_stage:
            stage_txt += (
                f"; read plane: {read_stage['stage']} "
                f"({read_stage['share_before']:.0%}->"
                f"{read_stage['share_during']:.0%})")
        if misspec and misspec["storm"]:
            stage_txt += (
                f"; mis-speculation storm ({misspec['misspec_rate']:.0%} of "
                f"{misspec['spec_dispatched']} speculated windows repaired)")
        if thrash and thrash["thrash"]:
            stage_txt += (
                f"; dictionary thrash ({thrash['promotions']} promotions vs "
                f"{thrash['demotions']} demotions — hot set exceeds the "
                f"HBM tier)")
        co_txt = ("; co-occurring: "
                  + ", ".join(_ann_brief(a) for a in co[:6])
                  if co else "; no co-occurring annotations")
        verdict["summary"] = (
            f"{inc['sli']} {inc['observed']} vs baseline "
            f"{inc['baseline_mean']} in [{t0:.1f},{t1:.1f}]s: "
            f"{stage_txt}{co_txt}")
        incidents.append(verdict)
    t_span = ([snaps[0]["t"], snaps[-1]["t"]] if snaps else None)
    return {
        "metric": "doctor_report",
        "ring": {
            "records": len(records),
            "snapshots": len(snaps),
            "annotations": len(anns),
            "scrape_gaps": len(gaps),
            "t_span": t_span,
        },
        "slo": tracker.status(),
        "incidents": incidents,
        "faults": attribute_faults(records, slack_s=slack_s),
        "scale_events": scale_relief(records, slack_s=slack_s),
    }


def attribute_faults(records: list[dict],
                     slack_s: float = SLACK_S,
                     grace_s: float = 20.0) -> list[dict]:
    """Per injected chaos fault: its window (fault stamp -> matching
    heal stamp for the same target, else +grace), the annotation classes
    found inside, and whether the EXPECTED class is among them."""
    _snaps, anns, _gaps = split_ring(records)
    faults = [a for a in anns if a.get("cls") == "chaos_fault"]
    heals = [a for a in anns if a.get("cls") == "chaos_heal"]
    out = []
    for f in faults:
        t0 = f["t"]
        heal = next((h for h in heals
                     if h.get("target") == f.get("target")
                     and h["t"] >= t0), None)
        t1 = heal["t"] if heal is not None else t0 + grace_s
        co = annotations_in(anns, t0, t1, slack_s,
                            exclude_cls=("chaos_fault", "chaos_heal"))
        classes = sorted({a.get("cls") for a in co})
        expected = EXPECTED_FAULT_CLASS.get(f.get("action"))
        out.append({
            "action": f.get("action"),
            "target": f.get("target"),
            "t": t0,
            "window": [t0, round(t1, 3)],
            "healed": heal is not None,
            "classes": classes,
            "expected_class": expected,
            "attributed": expected is None or expected in classes,
        })
    return out


# -- the CI gate ---------------------------------------------------------------


def run_doctor_gate(seed: int = 20260804, rate: float = 60.0,
                    workdir: "str | None" = None) -> dict:
    """tpuwatch ``doctor`` stage: seeded mini-chaos (loadgen/chaos.py
    --fast equivalent) with the flight recorder armed, then the doctor
    over the resulting ring — one JSON line gating EXACTLY:

    - the chaos battery itself passed (its own zero-loss/exactly-once
      gates — a doctor verdict about a broken run proves nothing);
    - every injected fault window is attributed to its expected
      annotation class;
    - the ring audit: snapshots present, every documented recorder_*/
      slo_* counter in the scrape, chaos fault/heal annotations ringed.
    """
    import os
    import tempfile

    from foundationdb_tpu.loadgen.chaos import run_chaos
    from foundationdb_tpu.obs.registry import RECORDER_DOCUMENTED_COUNTERS

    workdir = workdir or tempfile.mkdtemp(prefix="doctor_")
    ring_path = os.path.join(workdir, "flight_ring.jsonl")
    chaos_rec = run_chaos(seed=seed, fast=True, rate=rate, workdir=workdir,
                          recorder_path=ring_path)
    records = FlightRecorder.load(ring_path)
    report = diagnose(records)
    problems: list[str] = []
    if not chaos_rec.get("ok"):
        problems.append(
            f"chaos battery failed: {chaos_rec.get('problems')[:3]}")
    faults = report["faults"]
    if not faults:
        problems.append("no chaos_fault annotations reached the ring")
    unattributed = [f"{f['action']} {f['target']}@{f['t']:.1f}"
                    for f in faults if not f["attributed"]]
    if unattributed:
        problems.append(f"fault windows unattributed: {unattributed}")
    if report["ring"]["snapshots"] < 5:
        problems.append(
            f"only {report['ring']['snapshots']} snapshots ringed")
    snaps, _anns, _gaps = split_ring(records)
    last_metrics = (snaps[-1].get("metrics") or {}) if snaps else {}
    missing = [c for c in RECORDER_DOCUMENTED_COUNTERS
               if c not in last_metrics]
    if missing:
        problems.append(f"documented recorder counters missing: {missing}")
    slo = report["slo"]
    if not slo.get("windows"):
        problems.append("slo tracker evaluated zero windows")
    return {
        "metric": "doctor_gate",
        "ok": not problems,
        "problems": problems[:10],
        "seed": seed,
        "ring_path": ring_path,
        "chaos_ok": bool(chaos_rec.get("ok")),
        "snapshots": report["ring"]["snapshots"],
        "annotations": report["ring"]["annotations"],
        "faults": [{k: f[k] for k in ("action", "target", "expected_class",
                                      "classes", "attributed")}
                   for f in faults],
        "incidents": len(report["incidents"]),
        "slo_windows": slo.get("windows"),
        "slo_warmed_up": slo.get("warmed_up"),
        "replay": f"python -m foundationdb_tpu.obs --doctor-gate "
                  f"--seed {seed}",
    }


def main_doctor(ring_path: str, objectives: "dict | None" = None) -> dict:
    """`--doctor RING` / `cli doctor RING`: report over an existing ring."""
    records = FlightRecorder.load(ring_path)
    if not records:
        return {"metric": "doctor_report", "error":
                f"no records loaded from {ring_path!r}"}
    return diagnose(records, objectives)


if __name__ == "__main__":  # pragma: no cover - debugging convenience
    import sys

    print(json.dumps(main_doctor(sys.argv[1]), indent=1, sort_keys=True))
