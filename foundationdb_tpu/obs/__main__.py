"""CI entry point: one-JSON-line obs self-check / sampling-overhead A/B.

    python -m foundationdb_tpu.obs                   # selfcheck, rc 0/1
    python -m foundationdb_tpu.obs --ab              # OBS_AB.json record
    python -m foundationdb_tpu.obs --export-trace f  # Perfetto timeline
    python -m foundationdb_tpu.obs --poll cluster.json --poll-out m.jsonl
    python -m foundationdb_tpu.obs --record cluster.json \
        --record-out ring.jsonl                      # flight recorder
    python -m foundationdb_tpu.obs --doctor ring.jsonl   # incident report
    python -m foundationdb_tpu.obs --doctor-gate     # DOCTOR.json gate
    python -m foundationdb_tpu.obs --bench-history   # perf trajectory

The selfcheck (scrape + span reconciliation on a short sim run) is wired
as the `obs` stage of scripts/tpuwatch_r05.sh; the A/B is
scripts/obs_ab.sh -> OBS_AB.json. `--poll` is the deployed-cluster
time-series scraper (plain snapshots + scrape_gap records); `--record`
is the full flight recorder over a deployed cluster — bounded on-disk
ring with derived annotations and SLO tracking. `--doctor` runs the
incident doctor over an existing ring; `--doctor-gate` runs the seeded
mini-chaos with the recorder armed and gates the per-fault attribution
(scripts/doctor_run.sh -> DOCTOR.json, tpuwatch `doctor` stage).
`--bench-history` folds the committed BENCH_*/\\*_AB artifacts into the
time-ordered regression table (tpuwatch line).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: "list[str] | None" = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # pure sim: no TPU touch
    ap = argparse.ArgumentParser(prog="python -m foundationdb_tpu.obs")
    ap.add_argument("--ab", action="store_true",
                    help="sampling-overhead A/B (tracing off vs 1-in-N "
                         "vs 1-in-N + flight recorder) instead of the "
                         "selfcheck")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--txns", type=int, default=None)
    ap.add_argument("--sample-every", type=int, default=None)
    ap.add_argument("--reps", type=int, default=None,
                    help="--ab: reps per arm (best-of-N; default 3)")
    ap.add_argument("--export-trace", default=None, metavar="PATH",
                    help="also write the selfcheck run's sampled window "
                         "as a Chrome-trace/Perfetto JSON timeline")
    ap.add_argument("--poll", default=None, metavar="CLUSTER_JSON",
                    help="poll a DEPLOYED cluster's metrics into a JSONL "
                         "time-series instead of running the selfcheck")
    ap.add_argument("--poll-out", default="obs_metrics.jsonl")
    ap.add_argument("--poll-interval", type=float, default=5.0)
    ap.add_argument("--poll-count", type=int, default=0,
                    help="snapshots to take (0 = until interrupted)")
    ap.add_argument("--record", default=None, metavar="CLUSTER_JSON",
                    help="run the flight recorder against a DEPLOYED "
                         "cluster: bounded JSONL ring of snapshots + "
                         "derived annotations + SLO tracking")
    ap.add_argument("--record-out", default="flight_ring.jsonl")
    ap.add_argument("--record-interval", type=float, default=5.0)
    ap.add_argument("--record-count", type=int, default=0,
                    help="snapshots to take (0 = until interrupted)")
    ap.add_argument("--record-max", type=int, default=None,
                    help="ring bound in records (default 4096)")
    ap.add_argument("--doctor", default=None, metavar="RING_JSONL",
                    help="incident-doctor report over a flight ring")
    ap.add_argument("--doctor-gate", action="store_true",
                    help="seeded mini-chaos with the recorder armed, "
                         "gated on per-fault attribution (DOCTOR.json)")
    ap.add_argument("--bench-history", action="store_true",
                    help="fold committed BENCH_*/*_AB.json artifacts "
                         "into the time-ordered regression table")
    ap.add_argument("--history-root", default=".")
    args = ap.parse_args(argv)

    from foundationdb_tpu.obs.selfcheck import run_overhead_ab, run_selfcheck

    if args.bench_history:
        from foundationdb_tpu.obs.history import bench_history, format_table

        rec = bench_history(root=args.history_root)
        print(format_table(rec), file=sys.stderr, flush=True)
        print(json.dumps(rec), flush=True)
        return 0 if rec["ok"] else 1

    if args.doctor:
        from foundationdb_tpu.obs.doctor import main_doctor

        report = main_doctor(args.doctor)
        print(json.dumps(report, sort_keys=True), flush=True)
        return 0 if "error" not in report else 1

    if args.doctor_gate:
        from foundationdb_tpu.obs.doctor import run_doctor_gate

        kw = {}
        if args.seed is not None:
            kw["seed"] = args.seed
        rec = run_doctor_gate(**kw)
        print(json.dumps(rec), flush=True)
        return 0 if rec["ok"] else 1

    if args.record:
        from foundationdb_tpu.obs.recorder import FlightRecorder
        from foundationdb_tpu.obs.registry import scrape_deployed_async
        from foundationdb_tpu.runtime.net import NetTransport, RealLoop
        from foundationdb_tpu.server import load_spec

        spec = load_spec(args.record)
        loop = RealLoop()
        t = NetTransport(loop)
        recorder = FlightRecorder(
            loop, lambda: scrape_deployed_async(loop, t, spec),
            args.record_out, interval_s=args.record_interval,
            max_records=args.record_max)
        try:
            async def tick():
                await loop.sleep(recorder.interval_s)
                recorder.observe_registry(
                    await scrape_deployed_async(loop, t, spec))

            while (not args.record_count
                   or recorder.counters["recorder_snapshots"]
                   < args.record_count):
                loop.run(tick(), timeout=recorder.interval_s + 60.0)
        except KeyboardInterrupt:
            pass
        finally:
            recorder.close()
            t.close()
        print(json.dumps({"metric": "obs_record_done",
                          **recorder.metrics(),
                          "out": args.record_out}), flush=True)
        return 0

    if args.poll:
        import time

        from foundationdb_tpu.obs.registry import (
            scrape_deployed,
            scrape_gap_records,
        )
        from foundationdb_tpu.runtime.net import NetTransport, RealLoop
        from foundationdb_tpu.server import load_spec

        spec = load_spec(args.poll)
        loop = RealLoop()
        t = NetTransport(loop)
        # The shared gap bookkeeping rides this synchronous drive too: a
        # dead role must be an explicit scrape_gap record in the JSONL,
        # whichever surface runs the scrape loop. This drive stamps its
        # snapshot lines with WALL time, so the gap records ride the
        # same clock (MetricsPoller.run uses loop.now for both).
        armed_at = time.time()
        last_ok: dict = {}
        taken = gaps_written = 0
        try:
            while not args.poll_count or taken < args.poll_count:
                reg = scrape_deployed(loop, t, spec)
                now = time.time()
                lines = [json.dumps(r, sort_keys=True) for r in
                         scrape_gap_records(reg, now, last_ok, armed_at)]
                gaps_written += len(lines)
                lines.append(reg.to_json_line(
                    t=round(now, 3), seq=taken))
                with open(args.poll_out, "a", encoding="utf-8") as f:
                    f.write("\n".join(lines) + "\n")
                taken += 1
                if not args.poll_count or taken < args.poll_count:
                    time.sleep(args.poll_interval)
        except KeyboardInterrupt:
            pass
        finally:
            t.close()
        print(json.dumps({"metric": "obs_poll_done", "snapshots": taken,
                          "scrape_gaps": gaps_written,
                          "out": args.poll_out}), flush=True)
        return 0

    if args.ab:
        kw = {k: v for k, v in (
            ("seed", args.seed), ("txns", args.txns),
            ("sample_every", args.sample_every), ("reps", args.reps),
        ) if v is not None}
        rec = run_overhead_ab(**kw)
        print(json.dumps(rec), flush=True)
        return 0 if rec["valid"] else 1

    kw = {k: v for k, v in (
        ("seed", args.seed), ("txns", args.txns),
        ("sample_every", args.sample_every),
        ("export_trace", args.export_trace),
    ) if v is not None}
    rec = run_selfcheck(**kw)
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
