"""CI entry point: one-JSON-line obs self-check / sampling-overhead A/B.

    python -m foundationdb_tpu.obs                   # selfcheck, rc 0/1
    python -m foundationdb_tpu.obs --ab              # OBS_AB.json record
    python -m foundationdb_tpu.obs --export-trace f  # Perfetto timeline
    python -m foundationdb_tpu.obs --poll cluster.json --poll-out m.jsonl

The selfcheck (scrape + span reconciliation on a short sim run) is wired
as the `obs` stage of scripts/tpuwatch_r05.sh; the A/B is
scripts/obs_ab.sh -> OBS_AB.json. `--poll` is the deployed-cluster
time-series scraper: one aggregated JSONL snapshot per interval, over
the cluster spec's TCP endpoints, until interrupted (or --poll-count).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv: "list[str] | None" = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # pure sim: no TPU touch
    ap = argparse.ArgumentParser(prog="python -m foundationdb_tpu.obs")
    ap.add_argument("--ab", action="store_true",
                    help="sampling-overhead A/B (tracing off vs 1-in-N) "
                         "instead of the selfcheck")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--txns", type=int, default=None)
    ap.add_argument("--sample-every", type=int, default=None)
    ap.add_argument("--export-trace", default=None, metavar="PATH",
                    help="also write the selfcheck run's sampled window "
                         "as a Chrome-trace/Perfetto JSON timeline")
    ap.add_argument("--poll", default=None, metavar="CLUSTER_JSON",
                    help="poll a DEPLOYED cluster's metrics into a JSONL "
                         "time-series instead of running the selfcheck")
    ap.add_argument("--poll-out", default="obs_metrics.jsonl")
    ap.add_argument("--poll-interval", type=float, default=5.0)
    ap.add_argument("--poll-count", type=int, default=0,
                    help="snapshots to take (0 = until interrupted)")
    args = ap.parse_args(argv)

    from foundationdb_tpu.obs.selfcheck import run_overhead_ab, run_selfcheck

    if args.poll:
        import time

        from foundationdb_tpu.obs.registry import scrape_deployed
        from foundationdb_tpu.runtime.net import NetTransport, RealLoop
        from foundationdb_tpu.server import load_spec

        spec = load_spec(args.poll)
        loop = RealLoop()
        t = NetTransport(loop)
        taken = 0
        try:
            while not args.poll_count or taken < args.poll_count:
                reg = scrape_deployed(loop, t, spec)
                with open(args.poll_out, "a", encoding="utf-8") as f:
                    f.write(reg.to_json_line(
                        t=round(time.time(), 3), seq=taken) + "\n")
                taken += 1
                if not args.poll_count or taken < args.poll_count:
                    time.sleep(args.poll_interval)
        except KeyboardInterrupt:
            pass
        finally:
            t.close()
        print(json.dumps({"metric": "obs_poll_done", "snapshots": taken,
                          "out": args.poll_out}), flush=True)
        return 0

    if args.ab:
        kw = {k: v for k, v in (
            ("seed", args.seed), ("txns", args.txns),
            ("sample_every", args.sample_every),
        ) if v is not None}
        rec = run_overhead_ab(**kw)
        print(json.dumps(rec), flush=True)
        return 0 if rec["valid"] else 1

    kw = {k: v for k, v in (
        ("seed", args.seed), ("txns", args.txns),
        ("sample_every", args.sample_every),
        ("export_trace", args.export_trace),
    ) if v is not None}
    rec = run_selfcheck(**kw)
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
