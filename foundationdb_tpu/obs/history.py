"""Perf-trajectory table: fold committed bench artifacts into one view.

``python -m foundationdb_tpu.obs --bench-history`` scans the repo root
for the committed ``BENCH_*.json`` / ``*_AB.json`` round artifacts and
folds them into one time-ordered regression table: (artifact, round,
metric, headline value, honesty flags) per row, ordered by the round
number embedded in the filename (``_rNN``; round-less artifacts sort
last by name). Drift check: for artifacts sharing a metric across
rounds, the latest/previous ratio is computed ONLY between records both
marked ``valid`` — a ``valid:false`` record (CPU fallback, failed gate,
harness error) appears in the table with its reasons but is REFUSED as
a ratio endpoint, never silently averaged in. Wired as a tpuwatch line
so every future round gets the drift check for free.
"""

from __future__ import annotations

import glob
import json
import os
import re

#: headline-value extraction per artifact metric name: (key, unit).
#: Artifacts not listed fall back to a "value"/"unit" pair if present.
HEADLINE_KEYS = {
    "resolved_txns_per_sec_per_chip": ("value", "txns/sec/chip"),
    "obs_sampling_overhead_ab": ("overhead_frac", "frac"),
    "wave_commit_ab": ("value", "goodput ratio"),
    "wave_mesh_ab": ("value", "goodput ratio"),
    "admission_ab": ("naive_ratio_mean", "goodput ratio"),
    "resident_ab_dictionary": ("host_pack_ratio", "pack ratio"),
    "sched_ab_fixed_vs_adaptive": ("p99_cut_x", "p99 cut"),
    "open_loop_scaleout": ("past_saturation_observed", "bool"),
    "deployed_chaos": ("ok", "bool"),
    "kernel_ab_packed_vs_unpacked": ("value", "ratio"),
}

#: drift beyond this fraction between consecutive VALID rounds of the
#: same metric is flagged (informational unless --gate).
DRIFT_FRAC = 0.20


def _round_of(name: str) -> "int | None":
    m = re.search(r"_r(\d+)", name)
    return int(m.group(1)) if m else None


def _load_record(path: str) -> "dict | None":
    """Whole-file JSON, else the last parseable JSON line. Wrapper dicts
    (the autopilot's {cmd, rc, tail, parsed} capture) unwrap to their
    `parsed` payload; a null payload means the round never produced a
    record — reported as unparsed, not dropped."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    rec = None
    try:
        rec = json.loads(text)
    except ValueError:
        for line in reversed(text.strip().splitlines()):
            try:
                rec = json.loads(line)
                break
            except ValueError:
                continue
    if isinstance(rec, dict) and set(rec) >= {"cmd", "rc"}:
        rec = rec.get("parsed")
    return rec if isinstance(rec, dict) else None


def _row(path: str, rec: "dict | None") -> dict:
    name = os.path.basename(path)
    row: dict = {"artifact": name, "round": _round_of(name)}
    if rec is None:
        row.update(parsed=False, valid=False,
                   note="no JSON record (failed/incomplete round)")
        return row
    metric = rec.get("metric")
    key, unit = HEADLINE_KEYS.get(metric, ("value", rec.get("unit")))
    value = rec.get(key)
    row.update(
        parsed=True,
        metric=metric,
        value=value,
        value_key=key,
        unit=unit,
        valid=bool(rec.get("valid", rec.get("ok", False))),
        cpu_fallback=rec.get("cpu_fallback"),
        p99_quotable=rec.get("p99_quotable"),
        backend=rec.get("backend"),
    )
    reasons = rec.get("invalid_reasons") or rec.get("problems")
    if reasons:
        row["invalid_reasons"] = reasons[:3]
    return row


def bench_history(root: str = ".",
                  drift_frac: float = DRIFT_FRAC) -> dict:
    """The one-JSON-line record (metric ``bench_history``): the table,
    plus per-metric drift ratios between consecutive valid rounds."""
    paths = sorted(
        set(glob.glob(os.path.join(root, "BENCH_*.json")))
        | set(glob.glob(os.path.join(root, "*_AB.json"))))
    # The tpuwatch stage writes THIS tool's output as BENCH_HISTORY_*.json
    # in the same root — folding a previous trajectory record in as a
    # bench row would make every table self-referential.
    paths = [p for p in paths
             if not os.path.basename(p).startswith("BENCH_HISTORY")]
    rows = [_row(p, _load_record(p)) for p in paths]
    # Time order: round number first (round-less last), then name.
    rows.sort(key=lambda r: (r["round"] is None, r["round"] or 0,
                             r["artifact"]))
    drift: list[dict] = []
    refused: list[dict] = []
    by_metric: dict[str, list[dict]] = {}
    for r in rows:
        if r.get("parsed") and r.get("metric") and isinstance(
                r.get("value"), (int, float)) and not isinstance(
                r.get("value"), bool):
            by_metric.setdefault(r["metric"], []).append(r)
    for metric, series in by_metric.items():
        valid = [r for r in series if r["valid"]]
        for r in series:
            if not r["valid"]:
                refused.append({"artifact": r["artifact"], "metric": metric,
                                "why": "valid:false — refused as a ratio "
                                       "endpoint"})
        for prev, cur in zip(valid, valid[1:]):
            if not prev["value"]:
                continue
            ratio = cur["value"] / prev["value"]
            drift.append({
                "metric": metric,
                "from": prev["artifact"],
                "to": cur["artifact"],
                "ratio": round(ratio, 4),
                "drifted": abs(ratio - 1.0) > drift_frac,
            })
    return {
        "metric": "bench_history",
        "ok": True,  # the scan itself; drift is the reader's signal
        "artifacts": len(rows),
        "parsed": sum(1 for r in rows if r.get("parsed")),
        "valid": sum(1 for r in rows if r.get("valid")),
        "rows": rows,
        "drift": drift,
        "drifted": [d for d in drift if d["drifted"]],
        "refused_for_ratio": refused,
        "drift_frac": drift_frac,
    }


def format_table(record: dict) -> str:
    """Human-readable trajectory table (stderr companion to the JSON)."""
    lines = [f"{'round':>5}  {'artifact':<28} {'metric':<32} "
             f"{'value':>12}  flags"]
    for r in record["rows"]:
        flags = []
        if not r.get("parsed"):
            flags.append("UNPARSED")
        if r.get("valid"):
            flags.append("valid")
        else:
            flags.append("INVALID")
        if r.get("cpu_fallback"):
            flags.append("cpu_fallback")
        if r.get("p99_quotable") is False:
            flags.append("p99!quotable")
        val = r.get("value")
        val = (f"{val:.4g}" if isinstance(val, (int, float))
               and not isinstance(val, bool) else str(val))
        lines.append(
            f"{str(r.get('round') or '-'):>5}  {r['artifact']:<28} "
            f"{str(r.get('metric') or '-'):<32} {val:>12}  "
            f"{','.join(flags)}")
    for d in record["drifted"]:
        lines.append(f"DRIFT {d['metric']}: {d['from']} -> {d['to']} "
                     f"ratio {d['ratio']}")
    return "\n".join(lines)
