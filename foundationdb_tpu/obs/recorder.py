"""Cluster flight recorder: event-annotated metric time-series on disk.

Every surface this repo had before answered "what is true NOW" (status
JSON, one scrape) or "what did one txn do" (span trees); the questions
incidents actually pose — *why did p99 spike at t=12s?* — need a
continuous timeline where metric movements and discrete cluster events
sit on the SAME clock. The FlightRecorder is that timeline:

- **Snapshots**: one per ``interval_s`` via the standard scrape contract
  (``async () -> MetricsRegistry`` — scrape_sim / scrape_deployed_async
  / any harness wrapper), stored per-process AND aggregated.
- **Annotations**: first-class discrete events injected onto the same
  timeline from three feeds:

  1. *trace listener* — loop-local TraceEvents in TRACE_CATALOG
     (ratekeeper limiting-reason transitions, recovery stage machine,
     resolver fail-safe, region failover, commit wedges) land with their
     exact emit time;
  2. *derived* — transitions computed between consecutive snapshots
     from pure counters, which is what a REMOTE recorder (scraping over
     TCP) can see: recovery_count deltas, resolver-queue soft/hard
     crossings (Ratekeeper RQ_SOFT/RQ_HARD), admission filter
     engage/release episode deltas, ratekeeper limiting_reason_code
     changes, resident-engine reshard/repack deltas. A derived class is
     suppressed while the trace listener already covered it this
     interval, so sim runs don't double-annotate;
  3. *direct* — harnesses call ``annotate()`` (chaos fault/heal stamps,
     open-loop load phases).

- **Scrape gaps**: a failed role probe is an explicit ``gap`` record
  (role, instance, reason, outage duration) — never a hole.
- **SLO**: every snapshot feeds the SloTracker (obs/slo.py); newly
  opened anomaly incidents ring an ``slo`` annotation, and the tracker's
  status is served as ``workload.slo``.

The on-disk form is a bounded JSONL ring: records append; when the file
holds 2x ``max_records`` lines it is COMPACTED (atomic rewrite from the
in-memory ring) — retention ≈ max_records × interval_s seconds, the
knob pair README's Observability section documents. ``load()`` reads a
ring back for obs/doctor.py.

Arming: ``SimCluster(recorder_path=...)``, ``server.py`` controller role
with ``FDB_TPU_RECORDER=<path>``, ``python -m foundationdb_tpu.obs
--record cluster.json``, or chaos runs via ``--recorder``. The recorder
attaches as ``loop.flight_recorder`` (the Tracer/SpanSink convention).
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Callable

from foundationdb_tpu.obs.slo import SloTracker

#: trace-event Type -> annotation class (the loop-local feed). These are
#: EXACT event names as emitted by the runtime — the README annotation
#: catalog and the doctor's attribution both key off the classes.
TRACE_CATALOG = {
    "RkLimitReasonChanged": "ratekeeper_limit",
    "MasterRecoveryTriggered": "recovery",
    "MasterRecoveryState": "recovery",
    "MasterRecoveryFailed": "recovery",
    "DeployedRecoveryComplete": "recovery",
    "WorkerFailureDetected": "recovery",
    "RegionFailover": "recovery",
    "ResolverFailSafeEngaged": "resolver_capacity",
    "ResolverFailSafeReleased": "resolver_capacity",
    "ResolverHistoryOverflow": "resolver_capacity",
    "CommitBatchWedged": "commit_wedge",
}

#: every annotation class the recorder can emit (docs + doctor contract).
ANNOTATION_CLASSES = (
    "ratekeeper_limit",
    "recovery",
    "resolver_queue",
    "resolver_capacity",
    "admission_filter",
    "reshard",
    "commit_wedge",
    "chaos_fault",
    "chaos_heal",
    "load_phase",
    "slo",
    "autoscale",
    "scrape_gap",
)


class FlightRecorder:
    #: ring bound (records, snapshots + annotations + gaps combined) and
    #: the snapshot cadence — retention ≈ max_records × interval_s.
    MAX_RECORDS = 4096
    INTERVAL_S = 5.0

    def __init__(self, loop, scrape: Callable, path: str,
                 interval_s: "float | None" = None,
                 max_records: "int | None" = None,
                 objectives: "dict | None" = None,
                 listen_trace: bool = True):
        self.loop = loop
        self.scrape = scrape  # async () -> MetricsRegistry
        self.path = path
        self.interval_s = (self.INTERVAL_S if interval_s is None
                           else float(interval_s))
        self.max_records = (self.MAX_RECORDS if max_records is None
                            else max(16, int(max_records)))
        self.slo = SloTracker(objectives)
        self.ring: deque[dict] = deque(maxlen=self.max_records)
        # Re-arming over an existing ring file (a controller restart —
        # the exact incident the recorder must survive) seeds the
        # in-memory ring from the file tail: compaction rewrites the
        # file FROM this deque, so starting it empty would wipe every
        # pre-restart record at the first compaction and leave the
        # post-mortem doctor without its pre-incident baseline.
        for rec in self.load(path)[-self.max_records:]:
            self.ring.append(rec)
        self.counters = {
            "recorder_snapshots": 0,
            "recorder_annotations": 0,
            "recorder_scrape_gaps": 0,
            "recorder_compactions": 0,
            "recorder_ring_records": 0,
        }
        self._seq = 0
        self._file_lines = self._existing_lines()
        self._armed_at = loop.now
        self._last_ok: dict[tuple, float] = {}  # (role, inst) -> last t
        self._prev_agg: "dict | None" = None
        self._prev_values: dict = {}
        self._prev_t = loop.now
        # Per-class stamp of the last LISTENER annotation: the derived
        # emitters skip a class the exact-time feed already covered this
        # interval (sim would otherwise double-annotate every event).
        self._listener_cls_t: dict[str, float] = {}
        self._listening = False
        tracer = getattr(loop, "tracer", None)
        if listen_trace and tracer is not None:
            tracer.listeners.append(self._on_trace)
            self._listening = True
        loop.flight_recorder = self

    # -- ring I/O --------------------------------------------------------------

    def _existing_lines(self) -> int:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

    def _write(self, rec: dict) -> None:
        self.ring.append(rec)
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        self._file_lines += 1
        if self._file_lines >= 2 * self.max_records:
            self._compact()

    def _compact(self) -> None:
        """Atomic rewrite from the in-memory ring: the on-disk file never
        holds more than 2x the ring bound, and a reader at any instant
        sees either the old file or the compacted one, never a torn mix."""
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in self.ring:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._file_lines = len(self.ring)
        self.counters["recorder_compactions"] += 1

    @staticmethod
    def load(path: str) -> list[dict]:
        """Read a ring file back (doctor ingestion). A torn final line —
        the writer died mid-append — is dropped, not fatal."""
        out: list[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            return []
        return out

    # -- annotations -----------------------------------------------------------

    def annotate(self, name: str, cls: str, t: "float | None" = None,
                 severity: str = "info", _from_listener: bool = False,
                 **details) -> None:
        """Ring one annotation onto the timeline. ``details`` must be
        JSON-able (harness callers pass plain scalars)."""
        rec = {
            "kind": "annotation",
            "t": round(self.loop.now if t is None else t, 6),
            "name": name,
            "cls": cls,
            "severity": severity,
        }
        for k, v in details.items():
            if k not in rec:
                rec[k] = v
        self.counters["recorder_annotations"] += 1
        if _from_listener:
            self._listener_cls_t[cls] = rec["t"]
        self._write(rec)

    def _on_trace(self, rec: dict) -> None:
        """Tracer listener: catalog events land with exact emit times."""
        cls = TRACE_CATALOG.get(rec.get("Type"))
        if cls is None:
            return
        details = {k: v for k, v in rec.items()
                   if k not in ("Time", "Type", "Severity", "Process")}
        details["process"] = rec.get("Process")
        self.annotate(rec["Type"], cls, t=rec["Time"],
                      severity=str(rec.get("Severity", "")),
                      _from_listener=True, **details)

    # -- derived annotations (pure counter plane) ------------------------------

    def _derived_ok(self, cls: str) -> bool:
        """False while the trace listener already annotated this class
        since the previous snapshot (exact-time feed wins)."""
        return self._listener_cls_t.get(cls, -1.0) < self._prev_t

    def _derive(self, t: float, agg: dict, per_values: dict) -> None:
        prev = self._prev_agg
        if prev is None:
            return

        def delta(key: str) -> float:
            return agg.get(key, 0) - prev.get(key, 0)

        # Ratekeeper limiting-reason transitions.
        if self._derived_ok("ratekeeper_limit"):
            code0 = prev.get("ratekeeper.limiting_reason_code")
            code1 = agg.get("ratekeeper.limiting_reason_code")
            flaps = delta("ratekeeper.limit_transitions")
            if code0 is not None and (code1 != code0 or flaps > 0):
                from foundationdb_tpu.runtime.ratekeeper import LIMIT_REASONS

                def reason(code):
                    c = int(code or 0)
                    return (LIMIT_REASONS[c] if 0 <= c < len(LIMIT_REASONS)
                            else f"code{c}")

                self.annotate(
                    "RkLimitReasonChanged", "ratekeeper_limit", t=t,
                    severity="warn" if reason(code1) != "none" else "info",
                    reason=reason(code1), previous=reason(code0),
                    transitions=int(flaps))
        # Completed recoveries.
        if self._derived_ok("recovery"):
            n = delta("controller.recovery_count")
            if n > 0:
                self.annotate(
                    "RecoveryCompleted", "recovery", t=t, severity="warn",
                    recoveries=int(n),
                    lock_s=agg.get("controller.recovery_lock_s"),
                    salvage_s=agg.get("controller.recovery_salvage_s"),
                    recruit_s=agg.get("controller.recovery_recruit_s"),
                    total_s=agg.get("controller.recovery_total_s"))
        # Resolver dispatch-queue soft/hard crossings (worst instance;
        # thresholds are the ratekeeper's own RQ knobs).
        from foundationdb_tpu.runtime.ratekeeper import Ratekeeper

        def worst_depth(values: dict) -> int:
            return max(
                (int(v) for k, v in values.items()
                 if k.split("#", 1)[0] == "resolver.queue_depth_hw"),
                default=0)

        d0, d1 = worst_depth(self._prev_values), worst_depth(per_values)
        lvl = ("hard" if d1 >= Ratekeeper.RQ_HARD
               else "soft" if d1 >= Ratekeeper.RQ_SOFT else "none")
        lvl0 = ("hard" if d0 >= Ratekeeper.RQ_HARD
                else "soft" if d0 >= Ratekeeper.RQ_SOFT else "none")
        if lvl != lvl0:
            name = {"hard": "ResolverQueueHard", "soft": "ResolverQueueSoft",
                    "none": "ResolverQueueRecovered"}[lvl]
            self.annotate(name, "resolver_queue", t=t,
                          severity="warn" if lvl != "none" else "info",
                          depth_hw=d1, previous_depth_hw=d0,
                          soft=Ratekeeper.RQ_SOFT, hard=Ratekeeper.RQ_HARD)
        # Admission filter engage/release episodes.
        eng = delta("commit_proxy.admission.engage_events")
        rel = delta("commit_proxy.admission.release_events")
        if eng > 0:
            self.annotate("AdmissionFilterEngaged", "admission_filter",
                          t=t, severity="warn", episodes=int(eng),
                          saturation=agg.get(
                              "commit_proxy.admission.saturation"))
        if rel > 0:
            self.annotate("AdmissionFilterReleased", "admission_filter",
                          t=t, episodes=int(rel))
        # Resident-engine reshard / forced repack.
        rs = delta("resolver.engine.auto_reshards")
        if rs > 0:
            self.annotate("EngineReshard", "reshard", t=t,
                          reshards=int(rs),
                          moved_shards=int(
                              delta("resolver.engine.reshard_moved_shards")))
        rp = delta("resolver.engine.full_repacks")
        if rp > 0:
            self.annotate("EngineRepack", "reshard", t=t,
                          severity="warn", repacks=int(rp),
                          evictions=int(delta("resolver.engine.evictions")))
        # Tiered-dictionary demotion traffic (FDB_TPU_DICT_HOT_CAPACITY;
        # the counter is always exported, so the delta is honestly zero
        # when tiering is off). Demotions are the tier working as
        # designed — info severity; sustained promotion≈demotion churn is
        # the doctor's dict_thrash verdict, not a per-scrape annotation.
        dm = delta("resolver.engine.demotions")
        if dm > 0:
            self.annotate("EngineDemotion", "reshard", t=t,
                          demotions=int(dm),
                          promotions=int(
                              delta("resolver.engine.promotions")),
                          cold_tier_keys=int(agg.get(
                              "resolver.engine.cold_tier_keys", 0)))

    # -- snapshots -------------------------------------------------------------

    def _gap_records(self, reg, t: float) -> list[dict]:
        from foundationdb_tpu.obs.registry import scrape_gap_records

        return [{"kind": "gap", **r}
                for r in scrape_gap_records(reg, t, self._last_ok,
                                            self._armed_at)]

    def observe_registry(self, reg) -> None:
        """Process ONE scrape into the ring: recorder/slo self-metrics
        ride the snapshot, gaps become records, derived annotations and
        the SLO tracker run off the aggregated view. Callable directly
        by tests/harnesses that already hold a registry."""
        t = self.loop.now
        self.counters["recorder_ring_records"] = len(self.ring)
        reg.add("recorder", "", dict(self.counters))
        reg.add("slo", "", self.slo.metrics())
        for gap in self._gap_records(reg, t):
            self.counters["recorder_scrape_gaps"] += 1
            self._write(gap)
        agg = reg.aggregated()
        self._derive(t, agg, dict(reg.values))
        for opened in self.slo.observe(t, agg):
            self.annotate(opened.pop("name"), "slo", t=t, severity="warn",
                          **opened)
        self._write({
            "kind": "snapshot",
            "t": round(t, 3),
            "seq": self._seq,
            "metrics": agg,
            "per_process": reg.snapshot(),
        })
        self._seq += 1
        self.counters["recorder_snapshots"] += 1
        self._prev_agg = agg
        self._prev_values = dict(reg.values)
        self._prev_t = t

    async def run(self) -> None:
        """The always-on loop (spawn as its own task/process)."""
        while True:
            await self.loop.sleep(self.interval_s)
            reg = await self.scrape()
            self.observe_registry(reg)

    # -- lifecycle / export ----------------------------------------------------

    def metrics(self) -> dict:
        """Documented recorder_* counters (registry plane)."""
        out = dict(self.counters)
        out["recorder_ring_records"] = len(self.ring)
        return out

    def close(self) -> None:
        """Detach the trace listener and drop the loop attachment (ring
        file stays — it IS the artifact)."""
        tracer = getattr(self.loop, "tracer", None)
        if self._listening and tracer is not None:
            try:
                tracer.listeners.remove(self._on_trace)
            except ValueError:
                pass
        self._listening = False
        if getattr(self.loop, "flight_recorder", None) is self:
            del self.loop.flight_recorder
