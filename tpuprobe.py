import time, sys
from foundationdb_tpu.utils import enable_compilation_cache
enable_compilation_cache()
import jax, jax.numpy as jnp
t0=time.perf_counter(); d = jax.devices(); print(f"devices {time.perf_counter()-t0:.1f}s", file=sys.stderr)
t0=time.perf_counter()
x = jax.jit(lambda a: (a @ a).sum())(jnp.ones((259,259)))
float(x)
print(f"compile+run: {time.perf_counter()-t0:.1f}s", file=sys.stderr)
